"""Dynamic-batched inference serving: request coalescing over a
bucketed, precompiled eval step.

Reference: optim/PredictionService.scala:56 keeps an instance pool of
model clones behind a blocking queue -- concurrency there means more
JVM threads each running their own forward.  On TPU one compiled
program already saturates the chip, so concurrency is won by BATCHING:
concurrent callers submit single activities to a bounded queue, a
dispatcher thread drains it under a ``max_batch_size`` /
``max_wait_ms`` deadline policy, and every tick runs ONE padded device
batch instead of N serialized batch-1 dispatches.  The pad target
comes from a bucket ladder (``buckets.BucketLadder``) so the compiled
executable cache has a small, closed, warmable key set -- steady-state
serving performs zero XLA compiles (``precompile``).

Three device layouts behind one engine:

- single device (default): the model's own placement, like Predictor;
- sharded (``mesh=``): the batch axis splits over the mesh's data axis
  (``parallel/zero.stage_batch_global`` -- the dp driver's staging
  path) with params replicated once, so one tick runs data-parallel
  over every chip;
- host-side round-robin (``round_robin=True``): the fallback when no
  mesh program is wanted -- whole ticks rotate across local devices
  with per-device weight replicas, the literal analogue of the
  reference's cloned-instance pool.

Every tick emits a ``kind: "inference"`` telemetry event extended with
queue depth, bucket id, batch fill fraction, pad waste and the
per-request latencies (``tools/obs_report.py`` "Serving" section).
"""

import collections
import logging
import threading
import time
from concurrent.futures import Future, TimeoutError as FutureTimeoutError
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.dataset.minibatch import PaddingParam, Sample, \
    samples_to_minibatch
from bigdl_tpu.observability.spans import span
from bigdl_tpu.optim.validation import compiled_eval_step
from bigdl_tpu.serving.buckets import (BucketLadder, ladder_or_default,
                                       pad_batch_axis, pad_length_axis,
                                       slice_batch_axis, walk_length_leaves)

log = logging.getLogger("bigdl_tpu.serving")


class EngineDraining(RuntimeError):
    """``submit()`` refused because the engine is draining: it stopped
    ADMITTING requests (``drain()``) while the dispatcher finishes the
    queue it already accepted.  The typed error lets a fleet router
    distinguish "this replica is mid-deploy, pick another" from a real
    serving failure -- a drained replica is healthy, just closed for
    business until ``undrain()``."""


class ServeFuture(Future):
    """Per-request handle: ``result(timeout)`` plus, once served, the
    ``bucket`` the request rode in and its end-to-end ``latency_s``."""

    def __init__(self):
        super().__init__()
        self.bucket: Optional[int] = None
        self.latency_s: Optional[float] = None
        self._t_submit = time.perf_counter()
        self._trace = None           # sampled TraceContext, or None


# --------------------------------------------------------------------------- #
# Eval backends: where a tick's padded batch actually runs.
# --------------------------------------------------------------------------- #

class _LocalEval:
    """Default single-device layout -- the model's own placement."""

    kind = "local"
    align = 1
    replicas = 1

    def __init__(self, model, compute_dtype=None):
        self.model = model
        self.step = compiled_eval_step(model, compute_dtype)

    def stage(self, params, mstate):
        # uncommitted jnp leaves, like init-time weights: a numpy tree
        # would key the jit cache differently and force one spurious
        # recompile on the first tick that serves it
        import jax.numpy as jnp

        return (jax.tree.map(jnp.asarray, params), mstate)

    def install(self, staged):
        # the local layout serves from the model's own tree (the engine
        # points the model at the staged params); nothing device-side
        pass

    def capture(self):
        return (self.model.parameters()[0], self.model.state())

    def eval(self, x, tick=0, weights=None):
        if weights is not None:
            return self.step(weights[0], weights[1], x)
        params, mstate = self.model.parameters()[0], self.model.state()
        return self.step(params, mstate, x)

    def precompile(self, sample_spec, buckets):
        params, mstate = self.model.parameters()[0], self.model.state()
        return self.step.precompile(params, mstate, sample_spec, buckets)


class _ShardedEval:
    """Data-parallel eval over the mesh's data axis: the batch axis is
    split across devices (the dp driver's ``_shard_batch`` staging
    path, ``parallel/zero.stage_batch_global``), params/state are
    replicated ON DEVICE once at construction (call ``refresh_params``
    after mutating the model's weights)."""

    kind = "sharded"

    def __init__(self, model, mesh, axis="data", compute_dtype=None):
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.model = model
        self.mesh = mesh
        self.axis = axis
        self.align = int(mesh.shape[axis])
        self.replicas = int(mesh.shape[axis])
        self.step = compiled_eval_step(model, compute_dtype)
        self._batch_sharding = NamedSharding(mesh, P(axis))
        self._rep = NamedSharding(mesh, P())
        self.refresh_params()

    def refresh_params(self):
        self.install(self.stage(self.model.parameters()[0],
                                self.model.state()))

    def stage(self, params, mstate):
        staged_p = jax.device_put(params, self._rep)
        staged_m = mstate if not jax.tree.leaves(mstate) else \
            jax.device_put(mstate, self._rep)
        return (staged_p, staged_m)

    def install(self, staged):
        # one tuple unpack = the atomic pointer swap a cutover rides on
        self._params, self._mstate = staged

    def capture(self):
        return (self._params, self._mstate)

    def _stage(self, x):
        from bigdl_tpu.parallel.zero import stage_batch_global

        return stage_batch_global(x, self._batch_sharding)

    def eval(self, x, tick=0, weights=None):
        params, mstate = weights if weights is not None \
            else (self._params, self._mstate)
        return self.step(params, mstate, self._stage(x))

    def precompile(self, sample_spec, buckets):
        return self.step.precompile(self._params, self._mstate, sample_spec,
                                    buckets, stage=self._stage)


class _RoundRobinEval:
    """Whole ticks rotate across local devices, each holding its own
    weight replica -- the host-side fallback when no mesh program is
    available, and the literal TPU analogue of the reference's pooled
    model clones (PredictionService.scala:64-77: N instances, each
    serving whole requests)."""

    kind = "round_robin"
    align = 1

    def __init__(self, model, devices=None, compute_dtype=None):
        self.model = model
        self.devices = list(devices) if devices else jax.local_devices()
        self.replicas = len(self.devices)
        self.step = compiled_eval_step(model, compute_dtype)
        self.refresh_params()

    def refresh_params(self):
        # per-device replicas (the "clone pool"), remade on demand
        self.install(self.stage(self.model.parameters()[0],
                                self.model.state()))

    def stage(self, params, mstate):
        return [jax.device_put((params, mstate), d) for d in self.devices]

    def install(self, staged):
        self._replicas = staged        # one list swap = atomic cutover

    def capture(self):
        return self._replicas

    def eval(self, x, tick=0, weights=None):
        dev = self.devices[tick % len(self.devices)]
        replicas = weights if weights is not None else self._replicas
        params, mstate = replicas[tick % len(self.devices)]
        return self.step(params, mstate, jax.device_put(x, dev))

    def precompile(self, sample_spec, buckets):
        # jax keys executables on placement too: warm every device
        total = 0
        for dev, (params, mstate) in zip(self.devices, self._replicas):
            total += self.step.precompile(
                params, mstate, sample_spec, buckets,
                stage=lambda t, _d=dev: jax.device_put(t, _d))
        return total


# --------------------------------------------------------------------------- #
# The engine.
# --------------------------------------------------------------------------- #


def _tree_spec(tree):
    """(labels, per-leaf (shape, dtype), treedef) of a weight tree --
    the structural contract refresh_params validates against.  Reads
    shape/dtype ATTRIBUTES only: no ``np.asarray`` on the leaves, so
    validating gigabytes of device-resident params moves zero bytes."""
    from jax.tree_util import keystr, tree_flatten_with_path

    leaves_with_path, treedef = tree_flatten_with_path(tree)
    labels = [keystr(p) for p, _ in leaves_with_path]

    def dtype_of(l):
        dt = getattr(l, "dtype", None)
        return str(dt if dt is not None else np.result_type(l))

    specs = [(tuple(np.shape(l)), dtype_of(l))
             for _, l in leaves_with_path]
    return labels, specs, treedef


def _spec_mismatch(expect, got, what):
    """First structural/shape/dtype difference between two _tree_spec
    results as a human-readable reason, or None when they match.

    Always names the FIRST mismatched tree path with both sides'
    shapes/dtypes (where each side has that leaf at all): the
    half-written-checkpoint drill's operator needs to know WHICH plane
    broke, not just that one did (docs/robustness.md, "Serving
    survives a bad refresh")."""
    e_labels, e_specs, e_def = expect
    g_labels, g_specs, g_def = got
    if e_def != g_def:
        e_map = dict(zip(e_labels, e_specs))
        g_map = dict(zip(g_labels, g_specs))
        for label in e_labels:          # first contract leaf not offered
            if label not in g_map:
                e = e_map[label]
                return (f"{what} tree structure differs at {label}: "
                        f"serving contract expects shape {e[0]} dtype "
                        f"{e[1]}, leaf missing from the incoming tree")
        for label in g_labels:          # first offered leaf not expected
            if label not in e_map:
                g = g_map[label]
                return (f"{what} tree structure differs at {label}: "
                        f"incoming tree carries an unexpected leaf "
                        f"(shape {g[0]} dtype {g[1]}) the serving "
                        f"contract has no plane for")
        for label, e in zip(e_labels, e_specs):   # same leaves, reshaped
            g = g_map.get(label)
            if g is not None and e != g:
                return (f"{what} leaf {label}: expected shape {e[0]} "
                        f"dtype {e[1]}, got shape {g[0]} dtype {g[1]}")
        return (f"{what} tree structure differs: same leaves, "
                f"different nesting (first leaf "
                f"{e_labels[0] if e_labels else '<empty tree>'})")
    for label, e, g in zip(e_labels, e_specs, g_specs):
        if e != g:
            return (f"{what} leaf {label}: expected shape {e[0]} "
                    f"dtype {e[1]}, got shape {g[0]} dtype {g[1]}")
    return None


class ServingEngine:
    """Coalescing, bucketed, (optionally) sharded inference server.

    >>> eng = ServingEngine(model, max_batch_size=32, max_wait_ms=2.0)
    >>> eng.precompile()                  # warm the whole bucket ladder
    >>> y = eng.predict(feature)          # blocking single request
    >>> fut = eng.submit(feature)         # or async; fut.result()

    Deadline policy: a tick dispatches as soon as ``max_batch_size``
    requests are pending, or when the OLDEST pending request has waited
    ``max_wait_ms`` -- the knob trading batch fill (throughput) against
    added latency at low offered load (docs/performance.md, "Inference
    serving").  ``queue_capacity`` bounds pending requests; a full
    queue back-pressures ``submit`` instead of growing without bound.

    A tick that raises (poisoned input, device error) fails only that
    tick's requests -- the exception is set on each of its futures (so
    every affected caller sees it) and the dispatcher keeps serving
    subsequent traffic.

    ``quantize=True`` serves the model's int8 post-training-quantized
    twin (``nn.quantized.quantize_model``) instead of the fp32 original
    on the SAME layout/ladder/precompile machinery: ~4x smaller device
    weights, int8 MXU matmuls, zero steady-state recompiles.  The fp32
    model object stays untouched and remains the refresh contract:
    ``refresh_params`` takes fp32 checkpoints and quantizes them at swap
    time (on the sharded mesh the staged replica tree is the int8
    payload+scales -- the blockwise-int8 wire stance of the PR 4
    collectives applied to the weight gather, EQuARX-style -- with the
    moved bytes recorded on the ``param_refresh`` audit event).  Pass a
    callable to use it as the quantizer's allow/deny ``select``
    predicate.  ``accuracy_gate`` (an
    ``optim.validation.AccuracyDeltaGate``, or a dict of its kwargs)
    compares fp32-vs-int8 outputs on a held-out batch at construction
    AND at every refresh: a swap whose divergence exceeds the tolerance
    is rejected through the ``param_refresh`` rejected-with-reason path
    and the engine keeps serving its current weights.

    ``kv_cache_dtype="int8"`` stores the paged generation pool as int8
    payloads plus per-(position, head) fp32 scales (~3.6x less KV
    memory at head_dim 32; the ledger's ``kv_cache`` split reports the
    real narrow bytes).  ``speculative=k`` decodes with the int8 twin
    drafting ``k`` tokens per tick and ONE fp32 forward verifying them
    -- the output stream is bit-identical to fp32-only decoding
    (greedy and seeded sampling both), it's just emitted 1..k+1 tokens
    per verify step.  Both need ``kv_cache='paged'``; ``accuracy_gate``
    composes with ``speculative`` to gate the drafter the same way it
    gates an int8 serving twin (docs/performance.md, "Generation
    serving").
    """

    def __init__(self, model, max_batch_size: int = 32,
                 max_wait_ms: float = 2.0, queue_capacity: int = 1024,
                 ladder: Optional[BucketLadder] = None,
                 length_ladder: Optional[BucketLadder] = None,
                 length_select=None,
                 feature_padding: Optional[PaddingParam] = None,
                 compute_dtype=None, mesh=None, axis: str = "data",
                 round_robin: bool = False, telemetry=None,
                 max_executables: Optional[int] = None,
                 quantize=False, accuracy_gate=None,
                 decode_slots: Optional[int] = None,
                 decode_max_len: Optional[int] = None,
                 prompt_ladder: Optional[BucketLadder] = None,
                 kv_cache: str = "paged", kv_block_size: int = 16,
                 kv_blocks: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 kv_cache_dtype: str = "fp32",
                 speculative: int = 0):
        if not model.is_built():
            raise ValueError("build the model (or train it) before serving")
        if max_batch_size < 1:
            raise ValueError(f"max_batch_size must be >= 1, got "
                             f"{max_batch_size}")
        if queue_capacity < 1:
            # 0 would make the first submit() wait on _not_full forever
            raise ValueError(f"queue_capacity must be >= 1, got "
                             f"{queue_capacity}")
        self.model = model
        self._compute_dtype = compute_dtype
        # the serving contract frozen at construction: refresh_params
        # validates any later weight swap against THIS tree structure +
        # shapes BEFORE touching the device caches, so a half-written
        # checkpoint mid-retrain raises cleanly and the engine keeps
        # serving the old weights (docs/robustness.md).  The contract is
        # always the FP32 tree -- a quantized engine still swaps fp32
        # checkpoints in, quantizing them itself at staging time.
        self._params_spec = _tree_spec(model.parameters()[0])
        self._mstate_spec = _tree_spec(model.state())
        self._quantized = bool(quantize)
        self._qselect = quantize if callable(quantize) else None
        if speculative < 0:
            raise ValueError(
                f"speculative must be >= 0 (draft tokens per verify "
                f"step; 0 disables), got {speculative}")
        self.speculative = int(speculative)
        if accuracy_gate is not None and not self._quantized \
                and not self.speculative:
            raise ValueError(
                "accuracy_gate compares the fp32 model against its int8 "
                "twin; it needs quantize=... (int8 serving) or "
                "speculative=k (int8 drafter) to have a candidate to "
                "gate")
        self._gate = self._make_gate(accuracy_gate)
        if self._quantized or self.speculative:
            from bigdl_tpu.nn.quantized import quantize_model

            # the int8 twin: same module tree, quantized params, its
            # own compiled-step cache; self.model stays fp32.  On a
            # quantized engine it SERVES; with speculative=k it DRAFTS
            # (verification always runs the fp32 original, so the
            # generated stream stays bit-identical to fp32 decoding)
            self._qmodel, _ = quantize_model(model, select=self._qselect)
        else:
            self._qmodel = None
        serve_model = self._qmodel if self._quantized else model
        if mesh is not None and int(mesh.shape[axis]) > 1:
            self._backend = _ShardedEval(serve_model, mesh, axis,
                                         compute_dtype)
        elif round_robin and len(jax.local_devices()) > 1:
            self._backend = _RoundRobinEval(serve_model,
                                            compute_dtype=compute_dtype)
        else:
            self._backend = _LocalEval(serve_model, compute_dtype)
        align = self._backend.align
        self.max_batch_size = -(-int(max_batch_size) // align) * align
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.queue_capacity = int(queue_capacity)
        self.ladder = ladder_or_default(ladder, self.max_batch_size, align)
        if self.ladder.max < self.max_batch_size:
            self.ladder.add(self.max_batch_size)
        if self.ladder.min > self.max_batch_size:
            raise ValueError(
                f"ladder's smallest rung {self.ladder.min} exceeds "
                f"max_batch_size {self.max_batch_size}: a tick can never "
                f"hold that many requests, so every dispatch would pad "
                f"past the largest batch it can ever fill")
        # copied like the batch ladder (ladder_or_default): over-max
        # lengths grow this ladder under traffic, and that growth must
        # not leak into a ladder the caller shares with other engines
        self.length_ladder = None if length_ladder is None \
            else length_ladder.copy()
        self.length_select = length_select
        self.feature_padding = feature_padding
        self.telemetry = telemetry
        self._explicit_bound = max_executables is not None
        if self._explicit_bound:
            # the bound lives on the per-(model, dtype) compiled step,
            # which validate()/Predictor/other engines on the same model
            # share -- it governs that one shared cache (last writer
            # wins), because the executable count being bounded IS the
            # shared jit cache's
            self._backend.step.max_executables = max_executables
        else:
            self._fit_bound(len(self.ladder))
        self._pending = collections.deque()
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        self._running = True
        self._tick = 0
        self._served = 0
        # drain seam (serving/fleet.py rolling deploys): _draining stops
        # ADMISSION only; the dispatcher keeps ticking until every
        # already-accepted future is resolved.  _in_tick counts requests
        # claimed off the queue but not yet resolved, so drain() can
        # wait for true quiescence (queue empty AND no tick in flight).
        self._draining = False
        self._in_tick = 0
        self._idle = threading.Condition(self._lock)
        self._gate_detail = None
        # staged-exposure seams (serving/deploy.py): a canary routes a
        # traffic fraction's ticks onto a staged candidate's weights; a
        # shadow mirrors a fraction of ticks (batch + live outputs) to
        # an off-request-path observer.  Written by the rollout
        # controller's thread, read once per tick by the dispatcher --
        # single-attribute assignment keeps each swap atomic.
        self._canary = None           # (staged handle, fraction, version)
        self._canary_acc = 0.0
        self._canary_ticks = 0        # ticks served on the candidate
        self._canary_rows = 0         # real rows served on the candidate
        self._canary_failures = 0     # candidate ticks that raised
        self._shadow = None           # (fn, fraction)
        self._shadow_acc = 0.0
        self._version_info = None     # {"version", "digest"} when deployed
        # autoregressive generation (serving/generation.py): a slot
        # pool this size decodes with KV caches behind ``generate()``.
        # None = AUTO (8 slots when the served model has a decode mode,
        # off otherwise); 0 disables explicitly.  The scheduler is
        # built lazily on first use, but unlike the first paged-cache
        # cut, precompile() warms generation whenever the model has
        # a decode mode (the zero-steady-state-recompile contract: the
        # first generate() after precompile must not pay compiles,
        # whether or not decode_slots was spelled out).
        if decode_slots is None:
            decode_slots = 8 if hasattr(model, "init_cache") else 0
        self.decode_slots = int(decode_slots)
        self.decode_max_len = decode_max_len
        self._prompt_ladder = prompt_ladder
        # paged-KV knobs (serving/paging.py): "paged" virtualizes the
        # generation cache into a block pool with prefix sharing,
        # chunked prefill and in-jit sampling; "contiguous" keeps the
        # PR 15 slots x max_len pool (greedy only -- the A/B baseline).
        # Models without init_paged_cache fall back to contiguous.
        if kv_cache not in ("paged", "contiguous"):
            raise ValueError(
                f"kv_cache must be 'paged' or 'contiguous', got "
                f"{kv_cache!r}")
        self.kv_cache = kv_cache
        if kv_cache_dtype not in ("fp32", "int8"):
            raise ValueError(
                f"kv_cache_dtype must be 'fp32' or 'int8', got "
                f"{kv_cache_dtype!r}")
        if kv_cache_dtype != "fp32" and kv_cache != "paged":
            raise ValueError(
                "int8 KV blocks live in the paged pool (per-block "
                "payload + scale leaves); kv_cache_dtype='int8' needs "
                "kv_cache='paged'")
        if self.speculative and kv_cache != "paged":
            raise ValueError(
                "speculative decoding rides the paged block table "
                "(drafter pool shares the verifier's allocator); "
                "speculative=k needs kv_cache='paged'")
        if (kv_cache_dtype != "fp32" or self.speculative) \
                and not hasattr(model, "init_paged_cache"):
            raise TypeError(
                f"{type(model).__name__} has no init_paged_cache(): "
                f"int8 KV blocks and speculative decoding need the "
                f"paged decode mode (TransformerLM has one)")
        self.kv_cache_dtype = kv_cache_dtype
        self.kv_block_size = int(kv_block_size)
        self.kv_blocks = kv_blocks
        self.prefill_chunk = prefill_chunk
        self._gen = None
        self._gen_lock = threading.Lock()
        self._memory_ledger = None
        if self._gate is not None:
            # the INITIAL quantization must clear the same bar a later
            # hot-swap would: a model this quantizer damages beyond
            # tolerance never starts serving int8 at all
            ok, detail = self._check_accuracy(model.parameters()[0],
                                              model.state())
            self._gate_detail = detail
            if not ok:
                self._record_refresh("rejected", detail.get("reason"),
                                     accuracy_gate=detail)
                raise ValueError(
                    f"accuracy gate refused the initial int8 "
                    f"quantization ({detail.get('reason')}); serve fp32 "
                    f"or relax the gate tolerances")
        self._stamp_serving_info()
        self._dispatcher = threading.Thread(
            target=self._loop, name="bigdl-serving-dispatcher", daemon=True)
        self._dispatcher.start()

    # ----- request surface -------------------------------------------------- #
    def submit(self, feature, timeout: Optional[float] = None,
               trace=None) -> ServeFuture:
        """Enqueue one activity (array tree or ``Sample``); returns a
        future.  Blocks when ``queue_capacity`` requests are pending;
        with ``timeout``, a queue still full after that many seconds
        raises ``concurrent.futures.TimeoutError`` instead of waiting
        for the backlog to drain.  ``trace`` (an already-sampled
        ``TraceContext``) rides the future: the serving tick records
        queue-wait/device spans for it (docs/observability.md,
        "Request tracing")."""
        fut = ServeFuture()
        fut._trace = trace
        deadline = None if timeout is None \
            else time.perf_counter() + timeout
        with self._lock:
            if not self._running:
                raise RuntimeError("ServingEngine is closed")
            if self._draining:
                raise EngineDraining(
                    "ServingEngine is draining (admission closed until "
                    "undrain()); already-accepted requests will still "
                    "be served")
            while self._running and not self._draining and \
                    len(self._pending) >= self.queue_capacity:
                remaining = None if deadline is None \
                    else deadline - time.perf_counter()
                if remaining is not None and remaining <= 0:
                    raise FutureTimeoutError(
                        f"submit timed out after {timeout}s: queue full "
                        f"({self.queue_capacity} requests pending)")
                self._not_full.wait(timeout=remaining)
            if not self._running:
                raise RuntimeError("ServingEngine is closed")
            if self._draining:
                # drain began while this caller waited on a full queue:
                # admission is closed now, whatever space opened up
                raise EngineDraining(
                    "ServingEngine began draining while this submit "
                    "waited for queue space; request not accepted")
            self._pending.append((feature, fut))
            self._not_empty.notify()
        return fut

    def predict(self, feature, timeout: Optional[float] = None,
                trace=None):
        """Blocking single-request predict (the PredictionService
        surface): submit, wait, return this request's output rows.
        ``timeout`` bounds the WHOLE call -- admission into a full
        queue spends from the same budget as waiting for the result.
        A timed-out request is cancelled: if still pending, its tick
        drops it (a timeout/retry loop must not fill the queue with
        zombie requests nobody will read)."""
        t0 = time.perf_counter()
        fut = self.submit(feature, timeout=timeout, trace=trace)
        remaining = None if timeout is None \
            else max(0.0, timeout - (time.perf_counter() - t0))
        try:
            return fut.result(remaining)
        except FutureTimeoutError:
            self._abandon(fut)
            raise

    def predict_many(self, features, timeout: Optional[float] = None):
        """Submit a burst and wait for every result.  Like ``predict``,
        ``timeout`` bounds the WHOLE call (queue admission of each
        request and all the result waits draw down one shared budget --
        N requests never wait N times the timeout) and a timeout
        cancels every still-pending request of the burst."""
        deadline = None if timeout is None \
            else time.perf_counter() + timeout

        def remaining():
            return None if deadline is None \
                else max(0.0, deadline - time.perf_counter())

        futs: List[ServeFuture] = []
        try:
            for f in features:
                futs.append(self.submit(f, timeout=remaining()))
            return [f.result(remaining()) for f in futs]
        except FutureTimeoutError:
            for f in futs:
                self._abandon(f)     # no-op on already-served futures
            raise

    def _abandon(self, fut: ServeFuture):
        """Cancel a timed-out request AND free its queue slot now: a
        cancelled entry left in ``_pending`` would keep counting toward
        capacity / tick fill / the oldest-request deadline until a tick
        drained it, blocking the very retry the caller is about to
        make.  A ``GenerateFuture`` routes to ITS queue -- the
        generation scheduler's, not the predict deque."""
        from bigdl_tpu.serving.generation import GenerateFuture

        if isinstance(fut, GenerateFuture):
            if self._gen is not None:
                self._gen._abandon(fut)
            return
        if not fut.cancel():         # already claimed by a tick (or done)
            return
        with self._lock:
            for entry in self._pending:
                if entry[1] is fut:
                    self._pending.remove(entry)
                    self._not_full.notify()
                    break

    # ----- autoregressive generation (serving/generation.py) ----------------- #
    def _generation(self):
        """The lazily-built generation scheduler (slot pool + compiled
        prefill/decode steps).  Serves the SAME model the eval path
        serves: on a quantized engine that is the int8 twin, so
        generation rides the identical ``AccuracyDeltaGate``-guarded
        weight set every refresh_params swap validates."""
        if self._gen is None:
            with self._gen_lock:
                if self._gen is None:
                    if self.decode_slots < 1:
                        raise ValueError(
                            "generation is disabled on this engine "
                            "(decode_slots=0); construct with "
                            "decode_slots >= 1")
                    from bigdl_tpu.serving.generation import (
                        GenerateScheduler, PagedGenerateScheduler,
                        SpeculativeScheduler)

                    serve_model = self._qmodel if self._quantized \
                        else self.model
                    cache_dtype = {"fp32": jnp.float32,
                                   "int8": jnp.int8}[self.kv_cache_dtype]
                    paged_kw = dict(
                        slots=self.decode_slots,
                        max_len=self.decode_max_len,
                        prompt_ladder=self._prompt_ladder,
                        queue_capacity=self.queue_capacity,
                        cache_dtype=cache_dtype,
                        telemetry=self.telemetry,
                        admission_check=self._gen_admission_check,
                        exhausted_hook=self._on_pool_exhausted,
                        block_size=self.kv_block_size,
                        num_blocks=self.kv_blocks,
                        prefill_chunk=self.prefill_chunk)
                    if self.speculative:
                        # verifier = the fp32 original (the stream must
                        # stay bit-identical to fp32 decoding), drafter
                        # = the gated int8 twin
                        self._gen = SpeculativeScheduler(
                            self.model, self._qmodel,
                            spec_k=self.speculative, **paged_kw)
                    elif self.kv_cache == "paged" \
                            and hasattr(serve_model, "init_paged_cache"):
                        self._gen = PagedGenerateScheduler(
                            serve_model, **paged_kw)
                    else:
                        self._gen = GenerateScheduler(
                            serve_model, slots=self.decode_slots,
                            max_len=self.decode_max_len,
                            prompt_ladder=self._prompt_ladder,
                            queue_capacity=self.queue_capacity,
                            telemetry=self.telemetry,
                            admission_check=self._gen_admission_check,
                            exhausted_hook=self._on_pool_exhausted)
        return self._gen

    def _gen_admission_check(self):
        """Runs under the SCHEDULER's lock right before a generation
        enqueues: the engine-side lifecycle re-check that closes the
        race where drain() observes an idle scheduler between
        generate()'s early check and the actual enqueue."""
        if not self._running:
            raise RuntimeError("ServingEngine is closed")
        if self._draining:
            raise EngineDraining(
                "ServingEngine began draining while this generate "
                "was being admitted; request not accepted")

    def generate(self, prompt, max_new_tokens: int = 16,
                 eos_id: Optional[int] = None,
                 timeout: Optional[float] = None, trace=None,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, seed: Optional[int] = None):
        """Autoregressive generation: enqueue a prompt (1-D token ids)
        onto the continuous-batching decode scheduler; returns a
        streaming ``GenerateFuture`` (``.stream()`` yields tokens as
        decode ticks complete, ``.result()`` returns the full list).
        Generation stops at ``eos_id`` (included in the output) or
        after ``max_new_tokens``.

        Decoding is greedy by default; ``temperature > 0`` samples
        in-jit (optionally truncated by ``top_k`` / nucleus ``top_p``),
        with an explicit ``seed`` making the stream deterministic per
        (seed, prompt) -- sampling needs the paged scheduler
        (``kv_cache='paged'``, the default; the contiguous pool refuses
        it at submission).

        Admission honors the engine's lifecycle exactly like
        ``submit``: a draining engine raises ``EngineDraining``, a
        closed one ``RuntimeError``; ``timeout`` bounds the wait for a
        queue slot."""
        with self._lock:
            if not self._running:
                raise RuntimeError("ServingEngine is closed")
            if self._draining:
                raise EngineDraining(
                    "ServingEngine is draining (admission closed until "
                    "undrain()); in-flight generations still complete")
        sampling = None
        if temperature > 0.0 or top_k > 0 or top_p < 1.0 \
                or seed is not None:
            from bigdl_tpu.serving.sampling import SamplingParams

            sampling = SamplingParams(temperature=temperature,
                                      top_k=top_k, top_p=top_p,
                                      seed=seed)
        return self._generation().submit(prompt,
                                         max_new_tokens=max_new_tokens,
                                         eos_id=eos_id, timeout=timeout,
                                         trace=trace, sampling=sampling)

    def predict_at(self, feature, bucket: int):
        """UNBATCHED reference predict: this one request, padded to
        ``bucket``, evaluated synchronously outside the queue.  Within
        one bucket shape XLA's reduction order is fixed and eval-mode
        rows are independent, so this is bit-exact to the same request
        served in a coalesced tick of the same bucket (the bench's
        identical-outputs witness)."""
        x = self._form_batch([feature], bucket)
        y = self._backend.eval(x, tick=0)
        return jax.tree.map(lambda a: np.asarray(a)[0], y)

    def _fit_bound(self, n_buckets):
        """Raise the shared step's eviction-free executable bound to fit
        this engine's closed shape set (batch rungs x length rungs, x
        per-device replicas for round-robin) plus headroom for
        validation's own batch shape -- the default bound is sized for a
        single ladder and would cry "shape leak" on a legitimately
        warmed larger one.  No-op when the caller set an explicit
        ``max_executables`` (their bound, their warnings)."""
        if self._explicit_bound:
            return
        combos = n_buckets * (len(self.length_ladder)
                              if self.length_ladder is not None else 1)
        if isinstance(self._backend, _RoundRobinEval):
            combos *= len(self._backend.devices)
        step = self._backend.step
        step.max_executables = max(step.max_executables, combos + 8)

    # ----- warmup ----------------------------------------------------------- #
    def _sample_spec(self, example_feature=None):
        if example_feature is not None:
            feat = example_feature.feature \
                if isinstance(example_feature, Sample) else example_feature
            return jax.tree.map(np.asarray, feat)
        spec = getattr(self.model, "_build_spec", None)
        if spec is None:
            raise ValueError(
                "precompile() needs the per-sample feature shape: the "
                "model records none (built lazily?) -- pass "
                "example_feature=")
        # the build spec is batched: drop the leading batch axis
        return jax.tree.map(
            lambda s: np.zeros(tuple(s.shape[1:]), dtype=s.dtype), spec)

    def precompile(self, buckets=None, example_feature=None) -> int:
        """Compile the eval step for every bucket BEFORE traffic
        arrives; returns the number of backend compiles performed.
        After this, a workload of mixed request sizes within the
        ladder performs zero XLA compiles (the acceptance contract,
        pinned by tests/test_serving.py via ``RecompileWatchdog``).

        With a ``length_ladder``, every (batch bucket x length rung)
        combination is warmed -- each bucketed feature leaf's leading
        (time) axis is set to the rung, mirroring what
        ``pad_length_axis`` does to traffic (``length_select`` excludes
        fixed side inputs from both, and is always called with a
        BATCHED-rank leaf so a shape-based predicate selects the same
        leaves at warmup as under traffic).  A request mixing different
        rungs across bucketed leaves would still compile once on first
        sight."""
        spec = self._sample_spec(example_feature)
        if buckets is None:
            buckets = list(self.ladder)
        else:
            buckets = [int(b) for b in buckets]
            # the ladder= path validates this in ladder_or_default; an
            # explicit bucket list must not sneak past it into an opaque
            # sharding error when the batch can't split over the mesh
            bad = [b for b in buckets
                   if b < 1 or b % self._backend.align]
            if bad:
                raise ValueError(
                    f"buckets {bad} not divisible by the device alignment "
                    f"{self._backend.align} (sharded predict splits the "
                    f"batch axis evenly)")
        self._fit_bound(len(buckets))
        # generation's shape set (decode step + prefill rungs) warms
        # alongside the eval ladder, so one precompile() closes BOTH
        # executable sets before traffic.  Warm whenever the served
        # model HAS a decode mode: the old gate (explicit decode_slots=
        # or a scheduler already built) silently skipped AUTO-mode
        # engines, so their first generate() after "precompile" still
        # paid every generation compile (tests/test_paged.py pins this)
        gen_compiles = 0
        if self.decode_slots > 0 \
                and hasattr(self._qmodel if self._quantized
                            else self.model, "init_cache"):
            gen_compiles = self._generation().precompile()
        if self.length_ladder is None:
            return self._backend.precompile(spec, buckets) + gen_compiles

        total = gen_compiles
        for rung in self.length_ladder:
            # the same walker pad_length_axis uses under traffic, on
            # sample-rank spec leaves (batched=False): identical leaf
            # numbering, rank gate, and length_select semantics, so the
            # warmed shapes are exactly the ones ticks will produce
            at_rung = walk_length_leaves(
                spec, self.length_select,
                lambda a, _r=int(rung): np.zeros((_r,) + a.shape[1:],
                                                 a.dtype),
                batched=False)
            total += self._backend.precompile(at_rung, buckets)
        return total

    # ----- dispatcher ------------------------------------------------------- #
    def _loop(self):
        # a queue_capacity below max_batch_size caps how full a tick can
        # ever get -- waiting for more would stall EVERY tick for the
        # whole max_wait_ms at saturation (submitters blocked on a full
        # queue can never raise _pending past capacity)
        fill = min(self.max_batch_size, self.queue_capacity)
        while True:
            with self._lock:
                while self._running and not self._pending:
                    self._idle.notify_all()   # quiescent: drain() waiters
                    self._not_empty.wait()
                if not self._running and not self._pending:
                    self._idle.notify_all()
                    return
                # deadline anchored on the OLDEST pending request; a
                # draining engine dispatches immediately -- no new
                # requests can arrive, so waiting out max_wait_ms for a
                # fuller batch only delays the drain
                deadline = self._pending[0][1]._t_submit + self.max_wait_s
                while self._running and not self._draining \
                        and len(self._pending) < fill:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    self._not_empty.wait(timeout=remaining)
                take = min(self.max_batch_size, len(self._pending))
                reqs = [self._pending.popleft() for _ in range(take)]
                qdepth = len(self._pending)
                self._in_tick += len(reqs)
                self._not_full.notify_all()
            # claim each future (PENDING -> RUNNING) so a caller's
            # cancel() can no longer race the result-setting below --
            # set_result on a CANCELLED future raises InvalidStateError,
            # which would kill the dispatcher thread and hang the engine
            claimed = [r for r in reqs
                       if r[1].set_running_or_notify_cancel()]
            try:
                if claimed:
                    self._tick += 1
                    self._run_tick(claimed, qdepth)
            finally:
                with self._lock:
                    self._in_tick -= len(reqs)
                    self._served += len(claimed)
                    if not self._pending and not self._in_tick:
                        self._idle.notify_all()

    def _form_batch(self, features, bucket):
        samples = [f if isinstance(f, Sample) else Sample(f)
                   for f in features]
        mb = samples_to_minibatch(samples,
                                  feature_padding=self.feature_padding)
        x = pad_batch_axis(mb.get_input(), bucket)
        if self.length_ladder is not None:
            x = pad_length_axis(x, self.length_ladder, self.length_select)
        return x

    def _span(self, name, **kw):
        if self.telemetry is not None:
            return self.telemetry.span(name, **kw)
        return span(name, **kw)

    def _executables(self):
        """Current executable count of the shared compiled step (the
        per-tick delta is the live recompile signal: nonzero after
        ``precompile()`` means a shape leaked past the ladder).
        ``CompiledEvalStep.executables()`` already owns the
        can't-report fallback (None where jax lacks the cache API)."""
        return self._backend.step.executables() or 0

    def _run_tick(self, reqs, qdepth):
        t0 = time.perf_counter()
        feats = [r[0] for r in reqs]
        futs: List[ServeFuture] = [r[1] for r in reqs]
        execs_before = self._executables() \
            if self.telemetry is not None else 0
        # canary routing decided up front (error-diffusion accumulator:
        # a fraction f serves ~f of ticks on the candidate, spread
        # evenly, deterministically); the canary tuple is read ONCE so
        # a concurrent set_canary(None) cannot tear this tick
        canary = self._canary
        on_canary = False
        if canary is not None:
            self._canary_acc += canary[1]
            if self._canary_acc >= 1.0 - 1e-9:
                self._canary_acc -= 1.0
                on_canary = True
        reached_eval = False
        try:
            with self._span("serve_tick", tick=self._tick, records=len(reqs)):
                n = len(feats)
                bucket = self.ladder.bucket_for(n)
                if bucket is None:        # can't happen: take <= ladder.max
                    bucket = self.ladder.add(n)
                x = self._form_batch(feats, bucket)
                t_formed = time.perf_counter()
                reached_eval = True
                # weights= passed only on canary ticks: callers (and
                # tests) may substitute eval callables that predate
                # the override kwarg
                y = self._backend.eval(
                    x, tick=self._tick,
                    weights=canary[0]["staged"]) if on_canary \
                    else self._backend.eval(x, tick=self._tick)
                y = jax.tree.map(np.asarray, y)        # host sync + gather
        except Exception as e:
            # the failure belongs to THIS tick's callers only: surface
            # it on each future and keep the dispatcher serving
            log.exception("serving tick %d failed (%d requests)",
                          self._tick, len(futs))
            if on_canary and reached_eval:
                # a crashing candidate EVAL is canary evidence (the
                # rollout controller's rejection trigger); a malformed
                # request failing batch formation is the client's
                # fault on any tick and must not veto the rollout
                self._canary_failures += 1
            for fut in futs:
                if not fut.done():
                    fut.set_exception(e)
            return
        t_done = time.perf_counter()
        for i, fut in enumerate(futs):
            fut.bucket = bucket
            fut.latency_s = t_done - fut._t_submit
            fut.set_result(jax.tree.map(lambda a: a[i], y))
        if on_canary:
            self._canary_ticks += 1
            self._canary_rows += n
        # shadow mirroring AFTER the results are delivered: the
        # observer gets the tick's padded batch + live outputs and must
        # only enqueue (the candidate eval runs on the controller's
        # shadow worker, never on the request path)
        shadow = self._shadow
        if shadow is not None:
            self._shadow_acc += shadow[1]
            if self._shadow_acc >= 1.0 - 1e-9:
                self._shadow_acc -= 1.0
                try:
                    shadow[0](x, y, bucket, n, self._tick)
                except Exception:
                    log.exception("shadow observer failed (tick %d)",
                                  self._tick)
        if self.telemetry is not None:
            try:
                wall = t_done - t0
                event = dict(
                    step=self._tick, wall_s=wall,
                    data_wait_s=t_formed - t0, device_s=t_done - t_formed,
                    records=n, records_per_s=n / max(wall, 1e-9),
                    queue_depth=qdepth, queue_capacity=self.queue_capacity,
                    bucket=bucket, batch_fill=n / bucket,
                    pad_waste=(bucket - n) / bucket,
                    request_latency_s=[round(f.latency_s, 6) for f in futs])
                if on_canary:
                    # which ticks rode the candidate: the per-version
                    # SLO cut of the canary window reads this
                    event["canary"] = True
                    event["canary_version"] = canary[2]
                compiles = self._executables() - execs_before
                if compiles > 0:
                    # a tick that compiled: after precompile() this is
                    # a shape leak -- scrapeable live as
                    # bigdl_serving_recompiles_total
                    event["compiles"] = compiles
                traced = [f for f in futs if f._trace is not None]
                if traced:
                    # parallel trace-id list (null for untraced rows):
                    # the metrics bridge zips it with request_latency_s
                    # so latency-histogram buckets carry exemplars
                    event["request_traces"] = [
                        f._trace.trace_id if f._trace is not None
                        else None for f in futs]
                self.telemetry.record("inference", **event)
                if traced:
                    self._record_tick_trace(traced, t0, t_formed,
                                            t_done, bucket)
            except Exception:     # results are already delivered --
                log.exception(    # never let telemetry kill the dispatcher
                    "serving telemetry record failed (tick %d)", self._tick)

    def _record_tick_trace(self, traced, t0, t_formed, t_done, bucket):
        """Request-trace spans for one serving tick
        (docs/observability.md, "Request tracing"): one
        ``engine_request`` span per traced request (queue wait + device
        time under its own trace_id) and ONE ``serve_tick`` span
        carrying links to every trace riding the batch -- continuous
        batching means N request spans share one device dispatch."""
        emit = getattr(self.telemetry, "record_trace", None)
        if emit is None:
            return
        from bigdl_tpu.observability.tracing import TraceContext

        now = time.time()
        links = []
        for f in traced:
            ctx = f._trace.child()
            links.append(ctx.trace_id)
            emit("engine_request", ctx, now - f.latency_s, f.latency_s,
                 queue_wait_s=round(max(0.0, t0 - f._t_submit), 6),
                 device_s=round(t_done - t_formed, 6),
                 tick=self._tick, bucket=int(bucket))
        emit("serve_tick", TraceContext.mint(), now - (t_done - t0),
             t_done - t0, links=links, records=len(traced),
             tick=self._tick, bucket=int(bucket))

    # ----- int8 path: gate + staging helpers -------------------------------- #
    @property
    def quantized(self) -> bool:
        """Whether this engine serves the int8 twin (the precision that
        actually answers requests -- stamped on the telemetry header)."""
        return self._quantized

    def serving_model_bytes(self) -> int:
        """Bytes of the weight tree the backend serves from (the int8
        payload+scales tree when quantized, the fp32 tree otherwise)."""
        from bigdl_tpu.nn.quantized import model_bytes

        src = self._qmodel if self._quantized else self.model
        return model_bytes(src.parameters()[0])

    # ----- device-memory ledger (observability/memory.py) -------------------- #
    def memory_ledger(self, registry=None):
        """The engine's ``MemoryLedger``: params (plus the retained
        fp32 twin on a quantized engine), the KV block pool with its
        active/prefix-cached/free split, and -- when a deploy
        ``ModelRegistry`` is passed -- the staged-version buffers.
        Built lazily, attached to this engine's telemetry; call
        ``record_memory()`` to put a snapshot on the timeline.
        Re-calling with ``registry`` (re)binds the staged source."""
        if self._memory_ledger is None:
            from bigdl_tpu.observability.memory import MemoryLedger

            led = MemoryLedger()
            led.register("params", self.serving_model_bytes)
            if self._quantized:
                # the fp32 tree is retained for gate evals and as the
                # refresh_params source -- real bytes, own them
                def fp32_bytes():
                    from bigdl_tpu.nn.quantized import model_bytes
                    return model_bytes(self.model.parameters()[0])
                led.register("params_fp32", fp32_bytes)
            led.register("kv_cache", self._kv_cache_bytes)
            if self.telemetry is not None:
                led.attach(self.telemetry)
            self._memory_ledger = led
        if registry is not None:
            self._memory_ledger.register(
                "staged", lambda: registry.retained_bytes())
        return self._memory_ledger

    def _kv_cache_bytes(self):
        """Ledger source for the generation KV pool: total device bytes
        plus the allocator's block split (zero until the first
        ``generate()`` builds the scheduler)."""
        gen = self._gen
        if gen is None:
            return 0
        rec = {"bytes": gen.cache_bytes()}
        alloc = getattr(gen, "_alloc", None)
        if alloc is not None:
            st = alloc.stats()
            total = st.get("blocks_total") or 0
            # the allocator-reported bytes behind one addressable
            # block: measured from the device pool it fronts (payload
            # AND scale leaves at the pool's ACTUAL storage dtype), so
            # an int8 pool's split reports real narrow bytes instead
            # of compute-dtype hand-math overstating it ~4x
            per_block = st.get("bytes_per_block")
            if per_block is None:
                per_block = rec["bytes"] / total if total else 0
            rec.update(
                blocks_total=total,
                blocks_active=st.get("blocks_used"),
                blocks_cached=st.get("blocks_cached"),
                blocks_free=st.get("blocks_free"),
                kv_dtype=st.get("kv_dtype"),
                active_bytes=int(st.get("blocks_used", 0) * per_block),
                cached_bytes=int(st.get("blocks_cached", 0) * per_block),
                free_bytes=int(st.get("blocks_free", 0) * per_block))
        return rec

    def memory_headroom(self):
        """The admission/autoscaling capacity signal: allocator
        headroom (None on backends without memory stats) plus the KV
        pool's block occupancy, which is meaningful everywhere --
        ``BlockPoolExhausted`` sheds and autoscaler decisions cite
        these measured numbers."""
        snap = self.memory_ledger().snapshot()
        out = {"headroom_bytes": snap["headroom_bytes"],
               "headroom_fraction": snap["headroom_fraction"],
               "attributed_bytes": snap["attributed_bytes"],
               "live_bytes": snap["live_bytes"]}
        gen = self._gen
        alloc = getattr(gen, "_alloc", None) if gen is not None else None
        if alloc is not None:
            st = alloc.stats()
            total = st.get("blocks_total") or 0
            free = st.get("blocks_free", 0) + st.get("blocks_cached", 0)
            out["kv_blocks_total"] = total
            # cached blocks are reclaimable (LRU-evictable), so they
            # count as admission headroom even while they hold prefixes
            out["kv_blocks_free"] = free
            out["kv_fill"] = round(1.0 - free / total, 6) if total else 0.0
        return out

    def record_memory(self, **extra):
        """Snapshot the ledger onto the telemetry timeline (a durable
        ``kind: "memory"`` event, bridged to the
        ``bigdl_memory_bytes{device,subsystem}`` gauges)."""
        return self.memory_ledger().record(tick=self._tick, **extra)

    def _on_pool_exhausted(self, exc):
        """Generation's ``BlockPoolExhausted`` forensics hook: dump the
        full ledger + block occupancy + last ticks ONCE, durably,
        before/while the shed propagates to callers."""
        try:
            self.memory_ledger().handle_allocation_failure(
                exc, detail={"kv": self._kv_cache_bytes()},
                reason="kv_block_pool_exhausted")
        except Exception:
            log.exception("memory forensics dump failed")

    @staticmethod
    def _make_gate(accuracy_gate):
        if accuracy_gate is None:
            return None
        from bigdl_tpu.optim.validation import AccuracyDeltaGate

        if isinstance(accuracy_gate, AccuracyDeltaGate):
            return accuracy_gate
        if isinstance(accuracy_gate, dict):
            return AccuracyDeltaGate(**accuracy_gate)
        raise ValueError(
            f"accuracy_gate must be an AccuracyDeltaGate or a dict of "
            f"its kwargs, got {type(accuracy_gate).__name__}")

    def _gate_eval(self, step, params, mstate):
        """Bind ``step`` into the gate's ``x -> logits`` callable.  The
        held-out batch is padded to its ladder bucket (and the result
        sliced back), so the int8 side reuses a precompiled executable
        where possible -- gate evals run at swap time, never on the
        request path."""
        def run(x):
            x = jax.tree.map(np.asarray, x)
            n = jax.tree.leaves(x)[0].shape[0]
            bucket = self.ladder.bucket_for(n)
            xb = x if bucket is None or bucket == n \
                else pad_batch_axis(x, bucket)
            y = step(params, mstate, xb)
            return jax.tree.map(lambda a: np.asarray(a)[:n], y)
        return run

    def _check_accuracy(self, fp_params, fp_mstate, qparams=None):
        """fp32-vs-int8 gate on a CANDIDATE weight set (nothing is
        committed here): quantize ``fp_params`` unless the int8 tree is
        supplied, run both eval steps on the held-out batch, return
        ``(ok, detail)``."""
        if qparams is None:
            from bigdl_tpu.nn.quantized import quantize_params

            qparams = quantize_params(self.model, fp_params, self._qselect)
        from bigdl_tpu.optim.validation import compiled_eval_step

        ref_step = compiled_eval_step(self.model, self._compute_dtype)
        # the int8 side: the serving backend's step on a quantized
        # engine; on a speculative-only engine (fp32 serving, int8
        # drafter) the backend is fp32, so the gate evals the twin's
        # own compiled step instead
        q_step = self._backend.step if self._quantized \
            else compiled_eval_step(self._qmodel, self._compute_dtype)
        ok, detail = self._gate.check(
            self._gate_eval(ref_step, fp_params, fp_mstate),
            self._gate_eval(q_step, qparams, fp_mstate))
        return ok, detail

    def _stamp_serving_info(self):
        """Satellite of the int8 path: the telemetry header (or a
        standalone ``serving_info`` event when the header already went
        out) states which precision served this run -- quantized flag,
        weight dtype, serving-tree bytes (and the fp32 bytes it
        replaced), backend layout (docs/observability.md, "Serving
        telemetry")."""
        if self.telemetry is None:
            return
        from bigdl_tpu.nn.quantized import model_bytes

        info = {"quantized": self._quantized,
                "weight_dtype": "int8" if self._quantized else "float32",
                "model_bytes": self.serving_model_bytes(),
                "backend": self._backend.kind,
                "replicas": self._backend.replicas}
        if self.decode_slots > 0:
            info["decode_slots"] = self.decode_slots
            info["kv_cache"] = self.kv_cache
            if self.kv_cache == "paged":
                info["kv_block_size"] = self.kv_block_size
                info["kv_cache_dtype"] = self.kv_cache_dtype
            if self.speculative:
                info["speculative"] = self.speculative
        if self._version_info is not None:
            # WHICH checkpoint this replica serves: version id + the
            # snapshot's manifest digest (set_serving_version)
            info["version"] = self._version_info["version"]
            info["digest"] = self._version_info["digest"]
        if self._quantized:
            info["model_bytes_fp32"] = model_bytes(self.model.parameters()[0])
        if self._gate_detail is not None:
            info["accuracy_gate"] = self._gate_detail
        try:
            self.telemetry.set_serving_info(info)
        except Exception:
            log.exception("serving_info telemetry stamp failed")

    def _flush_prefix_cache(self):
        """After a weight swap lands: drop the paged scheduler's prefix
        cache.  Cached K/V was computed under the OLD weights -- serving
        it to a new prompt would silently mix checkpoints (live
        sequences keep their blocks and finish mid-flight, the PR 15
        trade)."""
        gen = self._gen
        flush = getattr(gen, "flush_prefix_cache", None)
        if flush is not None:
            flush()

    # ----- staged deployment surface (serving/deploy.py) --------------------- #
    def stage_weights(self, params, mstate=None, src_layout=None):
        """Validate + device-stage a CANDIDATE weight set WITHOUT
        committing anything: the engine keeps serving its current
        weights while the candidate's device buffers sit staged beside
        them.  Returns an opaque staged handle the rollout machinery
        threads through shadow evaluation (``eval_staged``), canary
        routing (``set_canary``) and the eventual atomic
        ``commit_staged`` -- or retains for a pointer-swap rollback.

        Same front door as ``refresh_params``: ``src_layout``
        redistributes a cross-layout checkpoint onto the serving tree
        first, then the structure/shape contract check runs -- a
        half-written checkpoint raises here, before any staging.  On a
        quantized engine the candidate is quantized ONCE at staging
        (the handle carries the int8 payload+scales); a later commit or
        rollback of this handle never re-quantizes or re-stages."""
        if src_layout is not None:
            from bigdl_tpu.parallel.reshard import to_model_layout

            params = to_model_layout(params, src_layout, self.model,
                                     telemetry=self.telemetry,
                                     what="deploy-stage")
        reason = self._validate_incoming(params, mstate)
        if reason is not None:
            raise ValueError(
                f"stage_weights rejected the candidate ({reason}); "
                f"nothing was staged -- is the source checkpoint "
                f"half-written or from a different model?")
        from bigdl_tpu.nn.quantized import model_bytes
        import jax.numpy as jnp

        # normalize to UNCOMMITTED jnp leaves here, so the tree a later
        # commit points the model at keys the jit cache exactly like
        # the init-time weights it replaces (a raw-numpy checkpoint
        # tree would force one spurious recompile on the first
        # post-cutover tick -- the zero-steady-state-recompile pin)
        params = jax.tree.map(jnp.asarray, params)
        if mstate is not None:
            mstate = jax.tree.map(jnp.asarray, mstate)
        stage_mstate = mstate if mstate is not None else self.model.state()
        qparams = None
        if self._quantized:
            from bigdl_tpu.nn.quantized import quantize_params

            qparams = quantize_params(self.model, params, self._qselect)
        serve_tree = qparams if qparams is not None else params
        return {"params": params, "mstate": mstate, "qparams": qparams,
                "staged": self._backend.stage(serve_tree, stage_mstate),
                "model_bytes": model_bytes(serve_tree),
                "quantized": self._quantized}

    def capture_staged(self):
        """The CURRENTLY serving weights as a staged handle -- what a
        rollout controller retains before a cutover so rollback is a
        pointer swap back to live device buffers, never a re-quantize
        or a re-stage."""
        from bigdl_tpu.nn.quantized import model_bytes

        qparams = self._qmodel.parameters()[0] if self._quantized else None
        serve_tree = qparams if qparams is not None \
            else self.model.parameters()[0]
        # the CURRENT model state rides the handle: a rollback must
        # restore it too, or a stateful model (BatchNorm running
        # stats) would serve previous params mixed with the rejected
        # candidate's state -- not the bit-for-bit re-serve promised
        return {"params": self.model.parameters()[0],
                "mstate": self.model.state(), "qparams": qparams,
                "staged": self._backend.capture(),
                "model_bytes": model_bytes(serve_tree),
                "quantized": self._quantized}

    def commit_staged(self, handle, version=None, digest=None):
        """The atomic cutover: point the engine at an already-staged
        handle.  The serving-visible swap is ONE attribute assignment
        (the backend's committed weights pointer / the served model's
        params dict), so a tick observes either the old weights or the
        new ones, never a torn mix -- and because the handle's device
        buffers already exist, this is equally the ROLLBACK primitive:
        committing a retained previous handle re-serves it bit-for-bit
        with no re-quantize, no re-stage, no gate.

        No gate runs here by design -- staged-exposure verdicts
        (shadow comparison, canary SLO + accuracy gate) belong to the
        rollout controller BEFORE it commits
        (docs/robustness.md, "Continuous deployment")."""
        if handle.get("quantized") != self._quantized:
            raise ValueError(
                "staged handle precision does not match this engine "
                "(was it staged on a different engine?)")
        if handle["qparams"] is not None:
            self._qmodel.set_parameters(handle["qparams"])
        self.model.set_parameters(handle["params"])
        if handle.get("mstate") is not None:
            self.model.set_state(handle["mstate"])
            if self._qmodel is not None:
                self._qmodel.set_state(handle["mstate"])
        self._backend.install(handle["staged"])
        if version is not None:
            self.set_serving_version(version, digest)
        audit = {"model_bytes": handle.get("model_bytes"), "staged": True}
        if self._quantized:
            audit["quantized"] = True
        if handle.get("wire_bytes") is not None:
            # weights that crossed the fleet wire record what the
            # TRANSPORT measured (int8 distribution ships ~4x fewer
            # bytes than model_bytes claims) -- the honest number for
            # the param_refresh trail
            audit["wire_bytes"] = int(handle["wire_bytes"])
            audit["weight_wire"] = handle.get("weight_wire")
        self._record_refresh("ok", **audit)
        self._flush_prefix_cache()
        self._stamp_serving_info()
        return self

    def eval_staged(self, handle, x, tick=0):
        """Run the serving eval step on a STAGED handle's weights --
        the shadow-evaluation path: same compiled executables as live
        traffic (identical shapes and placement, so zero new compiles
        for ladder-shaped batches), candidate outputs, nothing
        committed.  Runs on the caller's thread: keep it off the
        dispatcher (the shadow observer enqueues; a worker evals)."""
        y = self._backend.eval(x, tick=tick, weights=handle["staged"])
        return jax.tree.map(np.asarray, y)

    def set_canary(self, handle, fraction=0.1, version=None):
        """Route ``fraction`` of ticks onto a staged candidate's
        weights (error-diffused, so the fraction holds over any
        window); ``set_canary(None)`` ends the canary.  Stats reset on
        every call -- ``canary_stats()`` reads the current window."""
        if handle is not None and not 0.0 < float(fraction) <= 1.0:
            raise ValueError(
                f"canary fraction must be in (0, 1], got {fraction}")
        self._canary_acc = 0.0
        self._canary_ticks = 0
        self._canary_rows = 0
        self._canary_failures = 0
        self._canary = None if handle is None \
            else (handle, float(fraction), version)
        return self

    def canary_stats(self):
        """``{"ticks", "rows", "failures"}`` of the current canary
        window (since the last ``set_canary``)."""
        return {"ticks": self._canary_ticks, "rows": self._canary_rows,
                "failures": self._canary_failures}

    def set_shadow(self, fn, fraction=1.0):
        """Mirror ``fraction`` of ticks to ``fn(x_padded, y_live,
        bucket, n_real, tick)`` AFTER their results are delivered.
        The observer runs on the dispatcher thread and must only
        enqueue -- evaluate the candidate elsewhere (``eval_staged``).
        ``set_shadow(None)`` stops mirroring; observer exceptions are
        logged and swallowed (shadowing is best-effort, live traffic
        is not)."""
        if fn is not None and not 0.0 < float(fraction) <= 1.0:
            raise ValueError(
                f"shadow fraction must be in (0, 1], got {fraction}")
        self._shadow_acc = 0.0
        self._shadow = None if fn is None else (fn, float(fraction))
        return self

    def set_serving_version(self, version, digest=None):
        """Stamp WHICH model version this engine is serving: carried on
        the telemetry header's ``serving`` block (or a standalone
        ``serving_info`` event), every ``param_refresh`` audit event,
        and -- through the metrics bridge -- the
        ``bigdl_serving_version_info`` gauge, so an operator can always
        answer "which checkpoint is this replica serving?"."""
        self._version_info = {"version": int(version),
                             "digest": None if digest is None
                             else str(digest)}
        self._stamp_serving_info()
        return self

    def _validate_incoming(self, params, mstate):
        """First structure/shape/dtype mismatch of an incoming weight
        set against the construction-time serving contract, or None."""
        reason = _spec_mismatch(self._params_spec, _tree_spec(params),
                                "params")
        if reason is None and mstate is not None:
            reason = _spec_mismatch(self._mstate_spec, _tree_spec(mstate),
                                    "mstate")
        return reason

    # ----- lifecycle -------------------------------------------------------- #
    def refresh_from_snapshot(self, path):
        """Hot-swap weights straight from a TRAINING checkpoint written
        under ANY layout this stack trains (docs/robustness.md,
        "Portable resharding"): resolve the snapshot, read its manifest
        ``layout`` block, load the weights replicated on host under the
        snapshot's OWN layout, redistribute them onto the serving
        model's tree (``parallel/reshard.to_model_layout`` -- dp flat
        planes unravel, pp stage-stacked trees unstack, scan/unrolled
        block keyings interconvert, tp/ep trees pass through), and run
        the ordinary ``refresh_params`` -- structure check and
        ``accuracy_gate`` still in front, old weights keep serving on
        any rejection.

        ``path`` may be a snapshot itself (``checkpoint.<tag>.pkl`` /
        ``snap_<n>`` dir) or a checkpoint DIRECTORY, in which case the
        newest intact snapshot is resolved (corrupt ones quarantined,
        exactly like training resume)."""
        from bigdl_tpu.parallel.reshard import read_snapshot_layout

        p = self._resolve_snapshot(path)
        src = read_snapshot_layout(p)
        params, mstate = self._load_snapshot_weights(p, src)
        return self.refresh_params(params, mstate, src_layout=src)

    @staticmethod
    def _resolve_snapshot(path):
        """A concrete snapshot path from a file/dir/checkpoint-root
        (newest intact wins; every-candidate-corrupt raises)."""
        import os

        from bigdl_tpu.utils import file_io

        base = os.path.basename(str(path).rstrip("/"))
        if file_io.isdir(path) and not base.startswith("snap_"):
            intact, quarantined = file_io.scan_sharded_snapshots(path)
            if not intact:
                intact, q2 = file_io.scan_checkpoints(path)
                quarantined += q2
            if not intact:
                raise ValueError(
                    f"no intact snapshot under {path}"
                    + (f" (quarantined: {quarantined})" if quarantined
                       else ""))
            return intact[0]
        return path

    def _load_snapshot_weights(self, p, src_layout):
        """-> (params, mstate) of a snapshot, replicated on host under
        its OWN layout (the restore-under-own-layout contract the
        redistribution engine expects).  dp flat planes come back as
        the flat vector (``src_layout`` tells refresh_params to
        unravel); strategy snapshots as their native trees."""
        from bigdl_tpu.utils import file_io

        def clean_state(mstate):
            import jax
            return mstate if mstate is not None \
                and jax.tree.leaves(mstate) else None

        if not file_io.isdir(p):                   # pickle snapshot
            import jax.numpy as jnp

            payload = file_io.load(p)
            mp = payload["model_params"]
            if isinstance(mp, dict) and "model_params_flat" in mp:
                mp = mp["model_params_flat"]
            # uncommitted jnp leaves, exactly like the orbax branch
            # below: file_io.load hands back raw numpy, which keys the
            # serving jit cache differently than init-time weights and
            # would force one spurious recompile per bucket on the
            # first post-swap ticks
            mp = jax.tree.map(jnp.asarray, mp)
            return mp, clean_state(payload.get("model_state"))
        import orbax.checkpoint as ocp                  # sharded (orbax)

        with ocp.StandardCheckpointer() as ckptr:
            # no abstract tree: the snapshot's own structure/shapes ARE
            # the contract here (restore-under-own-layout); arrays come
            # back whole on the local device, host-addressable
            restored = ckptr.restore(p)
        # re-materialize as UNCOMMITTED arrays (host round trip): a
        # committed orbax-restored array -- or a raw numpy leaf -- keys
        # the serving jit cache differently than the init-time weights
        # it replaces and would force one spurious recompile on the
        # first post-swap tick (the zero-steady-state-recompile pin)
        import jax.numpy as jnp

        restored = jax.tree.map(lambda a: jnp.asarray(np.asarray(a)),
                                restored)
        if "params_flat" in restored:              # dp flat-plane payload
            return restored["params_flat"], clean_state(
                restored.get("mstate"))
        return restored["params"], None

    def refresh_params(self, params=None, mstate=None, src_layout=None):
        """Swap in retrained weights and re-replicate the device caches
        (sharded / round-robin layouts hold weights on device).

        With ``params`` (and optionally ``mstate``): validate the
        incoming tree's STRUCTURE and per-leaf shapes/dtypes against
        the serving model's, and only then ``set_parameters`` + refresh
        -- a refresh fed from a half-written checkpoint mid-retrain
        raises ``ValueError`` here and the engine keeps serving the old
        weights untouched.  Without arguments (the historical spelling:
        caller already mutated ``self.model``), the model's CURRENT
        params are validated against the engine's construction-time
        spec before the device caches re-replicate.

        On a quantized engine the incoming checkpoint is ALWAYS fp32
        (the training side's tree): it is quantized here at swap time,
        gated by ``accuracy_gate`` (a failing gate rejects the swap
        through the same rejected-with-reason audit path and the old
        weights keep serving), and the tree staged onto the devices is
        the int8 payload+scales -- the ``param_refresh`` event records
        ``model_bytes`` and the replica-staging ``wire_bytes`` it moved
        in that blockwise-int8 wire stance (docs/performance.md, "Int8
        inference").

        ``src_layout`` (a ``parallel.reshard.LayoutSpec`` or its
        manifest dict) names the layout the incoming ``params`` were
        SAVED under when it differs from the serving model's own tree:
        the weights are first redistributed onto the serving layout
        (``to_model_layout`` -- emitting the durable ``kind:"reshard"``
        audit event), and only then hit the structure check and the
        accuracy gate, so a tp/pp/dp training checkpoint hot-swaps into
        a replicated (or sharded-mesh) serving engine with the exact
        same guards in front."""
        incoming = params is not None
        if src_layout is not None:
            if not incoming:
                raise ValueError(
                    "src_layout describes an INCOMING params tree; "
                    "pass params= alongside it")
            from bigdl_tpu.parallel.reshard import to_model_layout

            params = to_model_layout(params, src_layout, self.model,
                                     telemetry=self.telemetry,
                                     what="serving-refresh")
        if incoming:
            reason = self._validate_incoming(params, mstate)
            if reason is not None:
                self._record_refresh("rejected", reason)
                raise ValueError(
                    f"refresh_params rejected the incoming weights "
                    f"({reason}); the engine keeps serving its current "
                    "weights -- is the source checkpoint half-written "
                    "or from a different model?")
        else:
            params = self.model.parameters()[0]
            reason = _spec_mismatch(self._params_spec, _tree_spec(params),
                                    "params")
            if reason is not None:
                self._record_refresh("rejected", reason)
                raise ValueError(
                    f"refresh_params: the model's weights no longer "
                    f"match the serving contract ({reason}); device "
                    "caches left untouched")
        from bigdl_tpu.nn.quantized import model_bytes

        qparams, gate_detail, audit = None, None, {}
        if self._quantized:
            from bigdl_tpu.nn.quantized import quantize_params

            # stage WITHOUT committing: quantize the candidate, gate it,
            # and only then touch the models / device caches
            qparams = quantize_params(self.model, params, self._qselect)
            stage_mstate = mstate if mstate is not None \
                else self.model.state()
            if self._gate is not None:
                ok, gate_detail = self._check_accuracy(params, stage_mstate,
                                                       qparams)
                if not ok:
                    reason = ("accuracy gate: "
                              + gate_detail.get("reason", "failed"))
                    self._record_refresh("rejected", reason,
                                         accuracy_gate=gate_detail)
                    raise ValueError(
                        f"refresh_params rejected the incoming weights "
                        f"({reason}); the engine keeps serving its "
                        "current weights")
                self._gate_detail = gate_detail
            audit["model_bytes"] = model_bytes(qparams)
            audit["quantized"] = True
        else:
            audit["model_bytes"] = model_bytes(params)
        # bytes the swap stages onto devices: one serving tree per
        # replica (mesh size for sharded, device count for round-robin)
        audit["wire_bytes"] = audit["model_bytes"] * self._backend.replicas
        if incoming:
            self.model.set_parameters(params)
            if mstate is not None:
                self.model.set_state(mstate)
        if qparams is not None:
            self._qmodel.set_parameters(qparams)
            # the twin shares the eval state tree; re-sync in case the
            # refresh (or the caller, in the no-arg spelling) moved it
            self._qmodel.set_state(self.model.state())
        refresh = getattr(self._backend, "refresh_params", None)
        if refresh is not None:
            refresh()
        if gate_detail is not None:
            audit["accuracy_gate"] = gate_detail
        self._record_refresh("ok", **audit)
        self._flush_prefix_cache()
        self._stamp_serving_info()
        return self

    def _record_refresh(self, outcome, reason=None, **extra):
        """Weight-swap audit trail: every refresh_params outcome (ok or
        rejected) lands as a ``kind: "param_refresh"`` telemetry event
        -- the live counter behind it is how a retrain loop's hot-swap
        cadence (and its rejected half-written checkpoints) shows up on
        a /metrics scrape.  ``extra`` carries the int8 staging evidence:
        ``model_bytes`` / ``wire_bytes`` of the staged tree, the
        ``quantized`` stamp and the ``accuracy_gate`` detail."""
        if self.telemetry is None:
            return
        try:
            fields = {"tick": self._tick, "outcome": outcome,
                      "backend": self._backend.kind, **extra}
            if self._version_info is not None:
                fields.setdefault("version", self._version_info["version"])
                fields.setdefault("digest", self._version_info["digest"])
            if reason is not None:
                fields["reason"] = str(reason)[:300]
            self.telemetry.record("param_refresh", **fields)
        except Exception:
            log.exception("param_refresh telemetry record failed")

    @property
    def draining(self) -> bool:
        """True while admission is closed (``drain()`` .. ``undrain()``)."""
        return self._draining

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Gracefully quiesce WITHOUT closing: stop admitting (a new
        ``submit`` raises the typed ``EngineDraining``), let the
        dispatcher finish its in-flight tick and serve every
        already-queued request, and return once the engine is idle.

        The contract the fleet's rolling deploys ride on
        (docs/robustness.md, "Serving fleets"): NO accepted future is
        ever dropped -- every request admitted before ``drain()`` was
        called resolves normally (result or its tick's exception).
        Returns True when fully drained; False when ``timeout`` seconds
        passed with work still in flight (the engine KEEPS draining --
        call again to keep waiting, or ``undrain()`` to reopen).
        Idempotent; ``undrain()`` reopens admission."""
        deadline = None if timeout is None \
            else time.perf_counter() + timeout
        with self._lock:
            self._draining = True
            # wake the dispatcher out of its batch-fill wait AND any
            # submitter blocked on a full queue (it must see the drain
            # and raise instead of being admitted late)
            self._not_empty.notify_all()
            self._not_full.notify_all()
            while self._pending or self._in_tick:
                remaining = None if deadline is None \
                    else deadline - time.perf_counter()
                if remaining is not None and remaining <= 0:
                    return False
                self._idle.wait(timeout=remaining)
        if self._gen is not None:
            # in-flight generations are accepted work too: the no-
            # accepted-future-ever-dropped contract covers them, so the
            # drain waits for every live sequence to finish decoding
            remaining = None if deadline is None \
                else max(0.0, deadline - time.perf_counter())
            return self._gen.drain(timeout=remaining)
        return True

    def undrain(self):
        """Reopen admission after a ``drain()`` (the rolling deploy's
        per-replica drain -> cutover -> undrain step)."""
        with self._lock:
            self._draining = False
            self._not_full.notify_all()
        return self

    def stats(self):
        """Live engine occupancy -- the health/load signal a fleet
        router balances on: pending queue depth, requests claimed by
        the in-flight tick, lifetime ticks/requests served, and the
        drain flag."""
        with self._lock:
            stats = {"pending": len(self._pending),
                     "in_tick": self._in_tick,
                     "draining": self._draining,
                     "running": self._running,
                     "ticks": self._tick,
                     "served": self._served,
                     "queue_capacity": self.queue_capacity}
        if self._gen is not None:
            stats["generate"] = self._gen.stats()
        return stats

    def close(self, timeout: Optional[float] = 10.0):
        """Stop accepting requests, drain the queue, join the
        dispatcher.  Idempotent."""
        with self._lock:
            self._running = False
            self._not_empty.notify_all()
            self._not_full.notify_all()
        self._dispatcher.join(timeout)
        if self._gen is not None:
            self._gen.close(timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
