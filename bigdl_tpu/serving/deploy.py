"""Versioned hot-swap deployment: the train->serve loop, closed.

ROADMAP item 5.  "Millions of users" means the model retrains while
the engine serves -- BigDL 2.0's end-to-end pipeline argument (arxiv
2204.01715) -- and the substrate for a safe swap has been accreting
for four PRs: ``refresh_from_snapshot`` + portable resharding (PR 12),
the ``AccuracyDeltaGate`` (PR 10), SLO burn-rate alerts + ``/healthz``
+ ``param_refresh`` audit counters (PR 9), crash-safe verified
snapshots (PR 8).  What was missing is the ORCHESTRATION: staged
exposure, rollback, and an answer to "which version is serving right
now?".  This module is that layer, rebuilding the reference's
Spark-lineage fault-tolerance story (arxiv 1804.05839 section 3) for
the serving half of the fleet the way PRs 8/12 rebuilt it for
training:

- ``ModelRegistry`` -- monotonic version ids, each carrying its
  snapshot path + manifest digest + layout.  The previous version's
  STAGED DEVICE BUFFERS are retained, so rollback is a pointer swap
  (``ServingEngine.commit_staged`` of the retained handle), never a
  re-quantize or a re-stage.  State persists durably (``registry.json``,
  temp-write + atomic rename) so a restarted process knows which
  version was live and re-serves it bit-for-bit from its verified
  snapshot.
- ``RolloutController`` -- watches a checkpoint directory (the one
  ``tools/train_supervised.py`` / ``tools/serve_live.py`` trainers
  write) through the same verified-intact resolution training resume
  uses, and walks each new snapshot through staged exposure:
  **shadow** (a fraction of live ticks is mirrored to the candidate
  OFF the request path; logits/top-1 compared via
  ``AccuracyDeltaGate.compare`` -- the canary-comparison signals PR 9
  promised), **canary** (a fraction of ticks SERVES on the candidate,
  with per-version health/SLO checks and the swap-time
  ``AccuracyDeltaGate``), then **atomic cutover**
  (``commit_staged``: one pointer assignment -- a tick sees old
  weights or new, never a torn mix).  A burning SLO, a gate refusal,
  a crashing canary tick or a watchdog anomaly rejects the candidate
  -- or, inside the post-cutover watch window, rolls the fleet back
  to the retained previous version.

Every stage lands as a durable ``kind: "deploy"`` telemetry event
(version, stage, verdict, reason, comparison stats), bridged to live
metrics (``bigdl_deploy_total{outcome}``,
``bigdl_serving_version_info``) and rendered by ``tools/obs_report.py``
in the Serving section.  Full story + the chaos drill:
docs/robustness.md, "Continuous deployment".

No jax at module top beyond what ``serving.engine`` already loaded:
the registry half is stdlib-only so a supervisor can parse
``registry.json`` without an accelerator.
"""

import hashlib
import json
import logging
import os
import queue
import threading
import time

log = logging.getLogger("bigdl_tpu.serving")

#: lifecycle stages a ModelVersion moves through (terminal:
#: rejected / rolled_back / retired)
VERSION_STAGES = ("registered", "shadow", "canary", "live", "previous",
                  "rejected", "rolled_back", "retired")

#: stages a ``kind: "deploy"`` event may carry (the schema pin in
#: tests/test_deploy.py holds this closed set)
DEPLOY_STAGES = ("registered", "shadow", "canary", "cutover", "live",
                 "rollback", "resume")

#: keys every deploy event carries
DEPLOY_EVENT_KEYS = ("version", "stage", "verdict", "reason")


def parse_deploy_chaos(spec):
    """``--chaos kill:cutover:<n>`` -> ``("kill", "cutover", n)``; None
    passes through.  The serving-side analogue of
    ``optim/recovery.parse_chaos``: SIGKILL the serving process at the
    MIDPOINT of its ``n``-th cutover (device buffers swapped, registry
    not yet committed).  A typo'd spec is a configuration error, not a
    silently-skipped drill."""
    if spec in (None, ""):
        return None
    from bigdl_tpu.utils.errors import ConfigurationError

    parts = str(spec).split(":")
    if len(parts) == 3 and parts[0] == "kill" and parts[1] == "cutover" \
            and parts[2].isdigit() and int(parts[2]) >= 1:
        return ("kill", "cutover", int(parts[2]))
    raise ConfigurationError(
        f"unknown deploy chaos spec {spec!r}; expected kill:cutover:<n> "
        "(SIGKILL the serving process mid-way through its n-th cutover)")


def parse_fleet_chaos(spec):
    """``--chaos kill:replica:<i>@<tick>`` -> ``("kill", i, tick)``;
    None passes through.  The fleet drill's fault injection
    (``tools/serve_fleet.py``): SIGKILL replica ``i``'s worker process
    once the closed-loop clients have completed ``tick`` requests --
    the retries must absorb it, the breaker must open, and the
    supervisor must bring the replica back on the committed version.
    A typo'd spec is a configuration error, not a silently-skipped
    drill."""
    if spec in (None, ""):
        return None
    from bigdl_tpu.utils.errors import ConfigurationError

    parts = str(spec).split(":")
    if len(parts) == 3 and parts[0] == "kill" and parts[1] == "replica":
        tail = parts[2].split("@")
        if len(tail) == 2 and tail[0].isdigit() and tail[1].isdigit() \
                and int(tail[1]) >= 1:
            return ("kill", int(tail[0]), int(tail[1]))
    raise ConfigurationError(
        f"unknown fleet chaos spec {spec!r}; expected "
        "kill:replica:<i>@<tick> (SIGKILL replica i's worker once the "
        "clients have completed <tick> requests)")


def snapshot_digest(path):
    """A short stable digest of a snapshot's sidecar manifest (the
    per-file sha256 map), or None for a manifest-less legacy snapshot.
    This is the identity a ``ModelVersion`` carries: two snapshots with
    the same digest hold bit-identical files, so the registry can tell
    "the trainer wrote something new" from "the same snapshot again"
    without hashing gigabytes twice (the manifest already did)."""
    from bigdl_tpu.utils import file_io

    manifest = file_io.read_manifest(path)
    if not manifest:
        return None
    files = manifest.get("files") or {}
    blob = json.dumps(sorted((k, v.get("sha256"))
                             for k, v in files.items()))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


class ModelVersion:
    """One registered model version: identity (id + snapshot path +
    manifest digest + layout), lifecycle ``stage``, and -- while
    retained -- the engine's staged device-buffer ``handle``."""

    def __init__(self, version, path=None, digest=None, layout=None,
                 stage="registered", handle=None, created=None):
        self.version = int(version)
        self.path = None if path is None else str(path)
        self.digest = digest
        self.layout = layout
        self.stage = stage
        self.handle = handle
        self.created = time.time() if created is None else created
        self.stats = {}

    def to_manifest(self):
        return {"version": self.version, "path": self.path,
                "digest": self.digest, "layout": self.layout,
                "stage": self.stage, "created": self.created}

    @classmethod
    def from_manifest(cls, d):
        return cls(d["version"], d.get("path"), d.get("digest"),
                   d.get("layout"), d.get("stage", "registered"),
                   created=d.get("created"))

    def describe(self):
        return (f"v{self.version}[{self.stage}]"
                + (f" {self.digest}" if self.digest else ""))


class ModelRegistry:
    """The versioned answer to "which checkpoint is serving?".

    >>> reg = ModelRegistry(os.path.join(out, "registry.json"))
    >>> v = reg.register(handle, path=snap, digest=digest)
    >>> reg.promote(v.version)        # v serves; the old live version's
    ...                               # staged buffers stay retained
    >>> reg.rollback()                # pointer swap back to it

    ``promote`` retains exactly live + previous staged handles (older
    versions drop their device buffers -- memory stays bounded no
    matter how many versions a long-lived fleet walks through); a
    version's IDENTITY (path/digest/layout/stage) is kept for every
    version and -- when a ``path`` was given at construction --
    persisted durably on every mutation (temp-write + atomic rename,
    the checkpoint discipline), so a SIGKILLed serving process restarts
    knowing exactly which version was live and re-stages it from its
    verified snapshot.
    """

    def __init__(self, path=None):
        self.path = None if path is None else str(path)
        self._lock = threading.RLock()
        self.versions = []
        self._live = None          # version id
        self._previous = None
        if self.path is not None and os.path.exists(self.path):
            self._load()

    # ----- lookups ----------------------------------------------------------- #
    def get(self, version):
        with self._lock:
            for v in self.versions:
                if v.version == int(version):
                    return v
        return None

    @property
    def live(self):
        return None if self._live is None else self.get(self._live)

    @property
    def previous(self):
        return None if self._previous is None else self.get(self._previous)

    def retained_bytes(self, include_live=False):
        """Device bytes of staged-version buffers the registry still
        retains, summed from each handle's ``model_bytes`` stamp.  The
        live version's buffers ARE the engine's serving params -- the
        ledger's ``params`` subsystem already owns them -- so they are
        excluded by default; what remains is the deploy tier's real
        extra footprint (the previous version kept for rollback plus
        any not-yet-promoted candidates).  This is the ``staged``
        source ``ServingEngine.memory_ledger(registry=...)`` wires in
        (observability/memory.py)."""
        with self._lock:
            total = 0
            for v in self.versions:
                if v.handle is None:
                    continue
                if not include_live and v.version == self._live:
                    continue
                b = v.handle.get("model_bytes") \
                    if isinstance(v.handle, dict) else None
                if b:
                    total += int(b)
            return total

    def known_digests(self):
        """Digests (and paths, for digest-less legacy snapshots) of
        every version ever registered -- the rollout watcher's
        already-seen set, so a restart does not re-deploy the snapshot
        that is already live."""
        with self._lock:
            out = set()
            for v in self.versions:
                if v.digest:
                    out.add(v.digest)
                elif v.path:
                    out.add(v.path)
            return out

    # ----- mutations ---------------------------------------------------------- #
    def register(self, handle, path=None, digest=None, layout=None):
        """A new version (monotonic id) holding a staged handle; stays
        ``registered`` until promoted/rejected."""
        with self._lock:
            vid = 1 + max((v.version for v in self.versions), default=0)
            v = ModelVersion(vid, path, digest, layout, handle=handle)
            self.versions.append(v)
            self._persist()
            return v

    def mark(self, version, stage):
        if stage not in VERSION_STAGES:
            raise ValueError(f"unknown version stage {stage!r}; expected "
                             f"one of {VERSION_STAGES}")
        with self._lock:
            v = self.get(version)
            if v is None:
                raise KeyError(f"unknown version {version}")
            v.stage = stage
            if stage in ("rejected", "rolled_back", "retired"):
                v.handle = None          # staged buffers released
            self._persist()
            return v

    def promote(self, version):
        """Make ``version`` live.  The old live version becomes
        ``previous`` WITH its staged buffers retained (the rollback
        target); anything older drops its handle."""
        with self._lock:
            v = self.get(version)
            if v is None:
                raise KeyError(f"unknown version {version}")
            if self._live is not None and self._live != v.version:
                old = self.get(self._live)
                old.stage = "previous"
                prev = self.get(self._previous) \
                    if self._previous is not None else None
                if prev is not None and prev.version != v.version:
                    prev.stage = "retired"
                    prev.handle = None
                self._previous = old.version
            v.stage = "live"
            self._live = v.version
            self._persist()
            return v

    def rollback(self):
        """Pointer swap back to the retained previous version: the
        rolled-back live version releases its buffers, ``previous``
        becomes live again (and there is no previous anymore -- a
        second rollback needs a new cutover first).  Returns
        ``(now_live, rolled_back)``."""
        with self._lock:
            prev = self.previous
            if prev is None:
                raise RuntimeError(
                    "rollback without a retained previous version "
                    "(nothing was ever cut over, or it was already "
                    "rolled back)")
            bad = self.live
            if bad is not None:
                bad.stage = "rolled_back"
                bad.handle = None
            prev.stage = "live"
            self._live = prev.version
            self._previous = None
            self._persist()
            return prev, bad

    def describe(self):
        with self._lock:
            return {"live": self._live, "previous": self._previous,
                    "versions": [v.to_manifest() for v in self.versions]}

    # ----- durability ---------------------------------------------------------- #
    def _persist(self):
        """Temp-write + atomic rename (the snapshot discipline): a
        writer SIGKILLed mid-persist leaves the previous registry
        state, never a truncated one -- which is exactly what the
        mid-cutover chaos drill leans on (docs/robustness.md)."""
        if self.path is None:
            return
        state = {"schema_version": 1, **self.describe()}
        tmp = self.path + f".tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(state, f, indent=1)
            f.flush()
            try:
                os.fsync(f.fileno())
            except OSError:      # pragma: no cover - exotic filesystems
                pass
        os.replace(tmp, self.path)

    def _load(self):
        with open(self.path) as f:
            state = json.load(f)
        self.versions = [ModelVersion.from_manifest(d)
                         for d in state.get("versions", [])]
        self._live = state.get("live")
        self._previous = state.get("previous")


class _ShadowStats:
    """Accumulated live-vs-candidate divergence over mirrored ticks,
    using ``AccuracyDeltaGate.compare`` per batch (THE one divergence
    definition) and aggregating row-weighted."""

    def __init__(self):
        self.rows = 0
        self.ticks = 0
        self.agree_rows = 0.0
        self.sq_sum = 0.0          # sum of squared logit deltas
        self.elements = 0

    def add(self, live_logits, cand_logits):
        import numpy as np

        from bigdl_tpu.optim.validation import AccuracyDeltaGate

        detail = AccuracyDeltaGate.compare(live_logits, cand_logits)
        n = detail["batch"]
        self.ticks += 1
        self.rows += n
        self.agree_rows += detail["top1_agreement"] * n
        size = int(np.asarray(live_logits).size)
        self.sq_sum += detail["logit_rmse"] ** 2 * size
        self.elements += size
        return detail

    @property
    def top1_agreement(self):
        return None if not self.rows else self.agree_rows / self.rows

    @property
    def logit_rmse(self):
        return None if not self.elements \
            else (self.sq_sum / self.elements) ** 0.5

    def summary(self):
        return {"shadow_ticks": self.ticks, "shadow_rows": self.rows,
                "top1_agreement": self.top1_agreement,
                "logit_rmse": self.logit_rmse}


class RolloutController:
    """Shadow -> canary -> atomic cutover -> (maybe) rollback.

    >>> ctl = RolloutController(engine, registry, ckpt_dir,
    ...                         telemetry=tel, health_sources=[slo.health_status])
    >>> ctl.baseline()              # the engine's boot weights = v1, live
    >>> ctl.serve_loop(stop_event)  # poll, stage, expose, promote

    Stage semantics (each emits a durable ``kind: "deploy"`` event):

    - ``registered``: the candidate snapshot passed verified-intact
      resolution, cross-layout redistribution and the structure check,
      and its device buffers are STAGED beside the serving ones.  A
      candidate that fails here is rejected before anything staged.
    - ``shadow``: ``shadow_fraction`` of live ticks is mirrored (batch
      + live outputs) to the controller, which evaluates the candidate
      OFF the request path and accumulates top-1 agreement + logit
      RMSE until ``shadow_min_rows`` real rows compared (or
      ``stage_timeout_s``).  Below ``min_top1_agreement`` / above
      ``max_logit_rmse`` -> rejected; a timeout with too little
      traffic -> rejected (an unverified candidate never advances).
    - ``canary``: ``canary_fraction`` of ticks SERVES on the candidate
      (tick events carry ``canary_version`` -- the per-version SLO
      cut).  Rejection triggers: a crashing candidate tick, a
      non-``ok`` health source (SLO burn / watchdog anomaly), or a
      failing ``accuracy_gate`` (live-vs-candidate on the held-out
      batch).
    - ``cutover`` / ``live``: ``ServingEngine.commit_staged`` -- one
      pointer assignment -- then the registry promotes durably.  The
      previous version's staged buffers stay retained.
    - ``rollback``: within ``post_cutover_watch_s`` after a cutover, a
      non-``ok`` health source rolls back to the retained previous
      version (pointer swap, no re-quantize/re-stage).  ``rollback()``
      may also be called directly (the operator's big red button).

    ``health_sources``: callables returning ``{"status": ...}``
    (``SloTracker.health_status``, ``MetricsRegistry.health``) -- the
    same ones ``/healthz`` aggregates, consulted at canary and in the
    post-cutover watch.  ``clock``/``sleep`` are injectable so tests
    drive stage windows without real waiting.  ``chaos`` is the fault
    hook of the drill: called as ``chaos(stage, version)`` mid-cutover
    (device buffers swapped, registry NOT yet committed -- the
    sharpest point to die at).
    """

    def __init__(self, engine, registry, checkpoint_dir=None,
                 telemetry=None, shadow_fraction=0.5, shadow_min_rows=32,
                 min_top1_agreement=0.98, max_logit_rmse=None,
                 canary_fraction=0.25, canary_min_ticks=4,
                 accuracy_gate=None, health_sources=(),
                 stage_timeout_s=60.0, post_cutover_watch_s=0.0,
                 reject_cooldown_s=300.0, drain_timeout_s=10.0,
                 replica_gate=None,
                 clock=time.monotonic, sleep=time.sleep, chaos=None):
        from bigdl_tpu.optim.validation import AccuracyDeltaGate

        self.engine = engine
        # fleet mode (serving/fleet.py): shadow/canary run on the
        # fleet's exposure replica, and the cutover becomes a ROLLING
        # deploy -- drain one replica, per-replica gate, commit,
        # undrain, proceed -- so the fleet never has zero serving
        # capacity and a failing gate rolls back only the replicas
        # already touched.  ``replica_gate(rid, fleet, handle) ->
        # (ok, reason)`` overrides the fleet's default probe gate.
        self._fleet = bool(getattr(engine, "is_fleet", False))
        self.drain_timeout_s = float(drain_timeout_s)
        self.replica_gate = replica_gate
        self.registry = registry
        self.checkpoint_dir = checkpoint_dir
        self.telemetry = telemetry
        self.shadow_fraction = float(shadow_fraction)
        self.shadow_min_rows = int(shadow_min_rows)
        self.min_top1_agreement = min_top1_agreement
        self.max_logit_rmse = max_logit_rmse
        self.canary_fraction = float(canary_fraction)
        self.canary_min_ticks = int(canary_min_ticks)
        if isinstance(accuracy_gate, dict):
            accuracy_gate = AccuracyDeltaGate(**accuracy_gate)
        self.accuracy_gate = accuracy_gate
        self.health_sources = list(health_sources)
        self.stage_timeout_s = float(stage_timeout_s)
        self.post_cutover_watch_s = float(post_cutover_watch_s)
        self.reject_cooldown_s = float(reject_cooldown_s)
        self.clock = clock
        self.sleep = sleep
        self.chaos = chaos
        self.events = []           # deploy events emitted this run
        # snapshots we never re-walk (served or still in flight); a
        # REJECTED snapshot instead gets a retry cooldown -- a
        # transient rejection (a momentary SLO burn, a traffic-quiet
        # shadow window) must not permanently discard the trainer's
        # newest checkpoint (in this process or after a restart)
        self._seen = set()
        self._rejected_until = {}
        for v in registry.versions:
            key = v.digest if v.digest else v.path
            if key is None:
                continue
            if v.stage == "rejected":
                self._rejected_until[key] = \
                    self.clock() + self.reject_cooldown_s
            else:
                self._seen.add(key)
        self._digest_cache = {}    # path -> (manifest stat, digest)
        self._watch_until = None   # post-cutover rollback window end

    # ----- deploy events ------------------------------------------------------ #
    def _emit(self, version, stage, verdict, reason=None, **stats):
        event = {"version": version.version, "stage": stage,
                 "verdict": verdict, "digest": version.digest,
                 "path": version.path}
        if reason is not None:
            event["reason"] = str(reason)[:300]
        for k, v in stats.items():
            if v is not None:
                event[k] = v
        self.events.append(event)
        if self.telemetry is not None:
            try:
                self.telemetry.record("deploy", **event)
            except Exception:
                log.exception("deploy telemetry record failed")
        log.info("deploy v%d %s: %s%s", version.version, stage, verdict,
                 f" ({reason})" if reason else "")
        return event

    # ----- bootstrap / resume -------------------------------------------------- #
    def baseline(self, path=None, digest=None):
        """Register the engine's CURRENT weights as the first live
        version (the boot state a first rollback would return to)."""
        handle = self.engine.capture_staged()
        v = self.registry.register(handle, path=path, digest=digest)
        self.registry.promote(v.version)
        self.engine.set_serving_version(v.version, v.digest)
        self._emit(v, "live", "ok", reason="baseline")
        return v

    def resume(self):
        """The restart path: re-serve the persisted registry's live
        version bit-for-bit from its verified snapshot.  An interrupted
        cutover (SIGKILL between the device swap and the registry
        commit) leaves the registry pointing at the PREVIOUS version --
        so that is what comes back, exactly as the chaos drill demands.
        Returns the live ModelVersion, or None (empty registry)."""
        live = self.registry.live
        if live is None:
            return None
        if live.path is None:
            # the baseline version (boot weights, no snapshot): the
            # restarted process rebuilt the same deterministic init --
            # re-capture it so a later cutover retains a rollback target
            live.handle = self.engine.capture_staged()
            self.engine.set_serving_version(live.version, live.digest)
            self._emit(live, "resume", "ok",
                       reason="baseline weights (no snapshot recorded)")
            return live
        params, mstate, src = self._load(live.path)
        digest = snapshot_digest(live.path)
        if live.digest is not None and digest != live.digest:
            raise RuntimeError(
                f"snapshot {live.path} no longer matches registry live "
                f"version v{live.version} (digest {digest} != "
                f"{live.digest}); refusing to serve an imposter")
        live.handle = self.engine.stage_weights(
            params, mstate, src_layout=src,
            **({"path": live.path} if self._fleet else {}))
        self.engine.commit_staged(live.handle, version=live.version,
                                  digest=live.digest)
        self._emit(live, "resume", "ok")
        return live

    # ----- the watcher ---------------------------------------------------------- #
    def poll_once(self):
        """One watch cycle: resolve the newest intact snapshot under
        ``checkpoint_dir`` (corrupt ones quarantined, exactly like
        training resume) and, when it is one we have not seen, walk it
        through the staged rollout.  Returns the resulting
        ModelVersion, or None when there is nothing new."""
        if self.checkpoint_dir is None \
                or not os.path.isdir(str(self.checkpoint_dir)):
            return None              # the trainer has not started yet
        from bigdl_tpu.serving.engine import ServingEngine

        try:
            path = ServingEngine._resolve_snapshot(self.checkpoint_dir)
        except ValueError:
            return None              # nothing intact (yet)
        digest = self._digest_of(path)
        key = digest if digest is not None else str(path)
        if key in self._seen:
            return None
        until = self._rejected_until.get(key)
        if until is not None:
            if self.clock() < until:
                return None          # rejected; cooling down to retry
            del self._rejected_until[key]
        self._seen.add(key)
        v = self.run_candidate(path, digest=digest)
        if v is not None and v.stage == "rejected":
            # eligible again after the cooldown -- the audit trail
            # records every retry as a fresh version id
            self._seen.discard(key)
            self._rejected_until[key] = \
                self.clock() + self.reject_cooldown_s
        return v

    def _digest_of(self, path):
        """``snapshot_digest`` cached on the sidecar manifest's stat
        (size + mtime): the idle poll cycle must not re-read and
        re-hash the manifest every interval -- but a snapshot
        re-written at the same path (a from-scratch retrain) is
        noticed."""
        mpath = str(path).rstrip("/") + ".manifest.json"
        try:
            st = os.stat(mpath)
            stamp = (st.st_size, st.st_mtime_ns)
        except OSError:
            return snapshot_digest(path)     # manifest-less legacy
        cached = self._digest_cache.get(str(path))
        if cached is not None and cached[0] == stamp:
            return cached[1]
        digest = snapshot_digest(path)
        self._digest_cache[str(path)] = (stamp, digest)
        return digest

    def serve_loop(self, stop=None, poll_interval_s=0.25):
        """Poll -> rollout -> post-cutover watch, until ``stop`` (a
        ``threading.Event``) is set.  The loop that
        ``tools/serve_live.py`` runs."""
        stop = stop or threading.Event()
        while not stop.is_set():
            self.poll_once()
            self.check_watch()
            self.sleep(poll_interval_s)
        return self

    # ----- the staged rollout --------------------------------------------------- #
    def _load(self, path):
        from bigdl_tpu.parallel.reshard import read_snapshot_layout
        from bigdl_tpu.serving.engine import ServingEngine

        p = ServingEngine._resolve_snapshot(path)
        src = read_snapshot_layout(p)
        params, mstate = self.engine._load_snapshot_weights(p, src)
        return params, mstate, src

    def run_candidate(self, path, digest=None):
        """Walk one candidate snapshot through the full staged
        exposure; returns its (terminal-or-live) ModelVersion."""
        if digest is None:
            digest = snapshot_digest(path)
        try:
            params, mstate, src = self._load(path)
            handle = self.engine.stage_weights(
                params, mstate, src_layout=src,
                **({"path": path} if self._fleet else {}))
        except Exception as e:
            v = self.registry.register(
                None, path=path, digest=digest)
            self.registry.mark(v.version, "rejected")
            self._emit(v, "registered", "rejected", reason=e)
            return v
        v = self.registry.register(
            handle, path=path, digest=digest,
            layout=None if src is None else src.to_manifest())
        self._emit(v, "registered", "ok",
                   model_bytes=handle.get("model_bytes"))

        ok, stats, reason = self._run_shadow(v, handle)
        self._emit(v, "shadow", "ok" if ok else "rejected",
                   reason=reason, **stats)
        if not ok:
            self._reject(v, handle)
            return v

        ok, stats, reason = self._run_canary(v, handle)
        self._emit(v, "canary", "ok" if ok else "rejected",
                   reason=reason, **stats)
        if not ok:
            self._reject(v, handle)
            return v

        return self._cutover(v, handle)

    def _reject(self, v, handle):
        if self._fleet:
            # drop the candidate's staged buffers fleet-wide (the
            # subprocess workers' token stores are bounded, not infinite)
            self.engine.release_staged(handle)
        self.registry.mark(v.version, "rejected")

    def _run_shadow(self, v, handle):
        """Mirror live traffic to the candidate off the request path;
        -> (ok, stats, reason)."""
        self.registry.mark(v.version, "shadow")
        stats = _ShadowStats()
        mirror = queue.Queue(maxsize=8)

        def observer(x, y, bucket, n, tick):
            try:                      # best-effort: drop when backed up
                mirror.put_nowait((x, y, n))
            except queue.Full:
                pass

        from bigdl_tpu.optim.validation import AccuracyDeltaGate

        self.engine.set_shadow(observer, self.shadow_fraction)
        deadline = self.clock() + self.stage_timeout_s
        try:
            while stats.rows < self.shadow_min_rows:
                remaining = deadline - self.clock()
                if remaining <= 0:
                    return False, stats.summary(), (
                        f"shadow window timed out with {stats.rows}/"
                        f"{self.shadow_min_rows} rows compared -- an "
                        f"unverified candidate never advances")
                try:
                    x, y, n = mirror.get(timeout=min(remaining, 0.25))
                except queue.Empty:
                    continue
                cand = self.engine.eval_staged(handle, x)
                live_l = AccuracyDeltaGate._logits(y)[:n]
                cand_l = AccuracyDeltaGate._logits(cand)[:n]
                stats.add(live_l, cand_l)
        finally:
            self.engine.set_shadow(None)
        agree = stats.top1_agreement
        if self.min_top1_agreement is not None \
                and agree is not None and agree < self.min_top1_agreement:
            return False, stats.summary(), (
                f"shadow top-1 agreement {agree:.4f} < required "
                f"{self.min_top1_agreement} over {stats.rows} mirrored "
                f"rows")
        rmse = stats.logit_rmse
        if self.max_logit_rmse is not None \
                and rmse is not None and rmse > self.max_logit_rmse:
            return False, stats.summary(), (
                f"shadow logit RMSE {rmse:.6g} > allowed "
                f"{self.max_logit_rmse}")
        return True, stats.summary(), None

    def _health(self):
        """Worst status across the health sources -> (status, reason)."""
        worst, why = "ok", None
        order = ("ok", "degraded", "halted")
        for src in self.health_sources:
            try:
                h = src()
            except Exception:
                log.exception("deploy health source %r failed", src)
                continue
            s = h.get("status", "ok")
            if s in order and order.index(s) > order.index(worst):
                worst = s
                reasons = h.get("reasons")
                why = reasons[0].get("reason") if reasons else s
        return worst, why

    def _run_canary(self, v, handle):
        """Serve a traffic fraction on the candidate; -> (ok, stats,
        reason)."""
        self.registry.mark(v.version, "canary")
        self.engine.set_canary(handle, self.canary_fraction,
                               version=v.version)
        deadline = self.clock() + self.stage_timeout_s
        try:
            while True:
                cs = self.engine.canary_stats()
                if cs["failures"]:
                    return False, cs, (
                        f"candidate tick(s) raised during canary "
                        f"({cs['failures']} failure(s))")
                status, why = self._health()
                if status != "ok":
                    return False, cs, (
                        f"health went {status} during canary ({why})")
                if cs["ticks"] >= self.canary_min_ticks:
                    break
                if self.clock() >= deadline:
                    return False, cs, (
                        f"canary window timed out with {cs['ticks']}/"
                        f"{self.canary_min_ticks} candidate ticks -- an "
                        f"unverified candidate never advances")
                self.sleep(0.02)
        finally:
            stats = self.engine.canary_stats()
            self.engine.set_canary(None)
        if self.accuracy_gate is not None:
            live = self.registry.live
            if live is not None and live.handle is not None:
                ok, detail = self.accuracy_gate.check(
                    self._bound_eval(live.handle),
                    self._bound_eval(handle))
                stats = {**stats, "accuracy_gate": detail}
                if not ok:
                    return False, stats, (
                        "accuracy gate: " + detail.get("reason", "failed"))
        return True, stats, None

    def _bound_eval(self, handle):
        """``x -> logits`` over a staged handle, bucket-padded so the
        gate eval reuses precompiled executables (never compiles on
        the request path)."""
        import jax
        import numpy as np

        from bigdl_tpu.serving.buckets import pad_batch_axis

        def run(x):
            x = jax.tree.map(np.asarray, x)
            n = jax.tree.leaves(x)[0].shape[0]
            bucket = self.engine.ladder.bucket_for(n)
            xb = x if bucket is None or bucket == n \
                else pad_batch_axis(x, bucket)
            y = self.engine.eval_staged(handle, xb)
            return jax.tree.map(lambda a: np.asarray(a)[:n], y)
        return run

    def _cutover(self, v, handle):
        """The atomic promotion: deploy event -> ONE pointer swap on
        the engine -> chaos hook (the drill dies HERE: buffers swapped,
        registry not yet committed -- a restart must still resolve the
        previous version) -> durable registry commit -> live event.
        On a fleet this becomes the ROLLING deploy instead."""
        if self._fleet:
            return self._rolling_cutover(v, handle)
        self._emit(v, "cutover", "ok")
        self.engine.commit_staged(handle, version=v.version,
                                  digest=v.digest)
        if self.chaos is not None:
            self.chaos("cutover", v)
        self.registry.promote(v.version)
        self._emit(v, "live", "ok")
        if self.post_cutover_watch_s > 0:
            self._watch_until = self.clock() + self.post_cutover_watch_s
        return v

    def _replica_gate(self, rid, handle):
        if self.replica_gate is not None:
            return self.replica_gate(rid, self.engine, handle)
        return self.engine.gate_replica(rid, handle)

    def _rolling_cutover(self, v, handle):
        """Fleet mode's cutover: replica-by-replica drain -> gate ->
        commit -> undrain, so the fleet never has zero serving capacity
        and the UNTOUCHED replicas keep serving the old version
        mid-roll.  A failing per-replica gate rolls back ONLY the
        replicas already cut over (pointer swaps to the pre-roll
        capture) and rejects the candidate; a replica that died
        mid-roll is skipped (the supervisor restarts it from the
        registry, which will then name the promoted version).

        The chaos hook fires after each per-replica commit with the
        registry still uncommitted -- the fleet drill's sharpest
        point."""
        fleet = self.engine
        live = self.registry.live
        prev = fleet.capture_staged()
        prev_per = prev.get("per_replica") or {}
        per = handle.get("per_replica") or {}
        touched = []

        def roll_back(reason):
            for rid in reversed(touched):
                try:
                    prev_h = prev_per.get(rid)
                    if prev_h is not None:
                        fleet.commit_replica(
                            rid, prev_h,
                            version=live.version if live else None,
                            digest=live.digest if live else None)
                    elif live is not None and live.path is not None:
                        # no pre-roll capture (the replica restarted
                        # mid-roll and was caught up onto the now-
                        # rejected candidate): restore from the live
                        # version's snapshot instead of stranding it
                        rep = fleet._by_id(rid)
                        fresh = rep.stage(path=live.path)
                        rep.commit(fresh, version=live.version,
                                   digest=live.digest)
                    else:
                        log.warning(
                            "rollback: no pre-roll capture for replica "
                            "%s and the live version has no snapshot; "
                            "its next restart reconciles it", rid)
                except Exception:
                    log.exception("rolling rollback of replica %s "
                                  "failed", rid)
            fleet.release_staged(handle)
            self.registry.mark(v.version, "rejected")
            self._emit(v, "rollback", "rolled_back", reason=reason,
                       rolled_back_to=live.version if live else None,
                       replicas=list(touched))

        for rid in sorted(per):
            rep = fleet._by_id(rid)
            if rep.state in ("dead", "closed"):
                # it missed the roll; boot-from-registry catches it up
                self._emit(v, "cutover", "ok", replica=rid,
                           reason="replica dead mid-roll; will boot "
                                  "from the registry's committed "
                                  "version")
                continue
            try:
                drained = fleet.drain_replica(
                    rid, timeout=self.drain_timeout_s)
                ok, reason = self._replica_gate(rid, handle)
            except Exception as e:
                ok, drained, reason = False, False, f"replica gate " \
                    f"raised: {e}"
            if not ok:
                # a replica that DIED here (vs. one whose gate judged
                # the candidate bad) is not the candidate's fault --
                # skip it like the commit path does, don't reject the
                # rollout fleet-wide
                alive = True
                try:
                    alive = rep.alive()
                except Exception:
                    alive = False
                if not alive:
                    fleet.mark_dead(rep,
                                    reason=f"died mid-drain/gate: "
                                           f"{reason}")
                    self._emit(v, "cutover", "ok", replica=rid,
                               reason="replica died mid-drain/gate; "
                                      "will boot from the registry")
                    continue
                try:
                    fleet.undrain_replica(rid)
                except Exception:
                    log.exception("undrain of replica %s failed", rid)
                self._emit(v, "cutover", "rejected", replica=rid,
                           reason=f"per-replica gate: {reason}")
                roll_back(f"per-replica gate failed on replica {rid} "
                          f"({reason}); {len(touched)} touched "
                          f"replica(s) rolled back, the rest never "
                          f"left the old version")
                return v
            try:
                fleet.commit_replica(rid, per[rid], version=v.version,
                                     digest=v.digest)
            except Exception as e:
                if not rep.alive():
                    # the process died under us: not the candidate's
                    # fault -- skip it, keep rolling
                    fleet.mark_dead(rep, reason=f"died mid-cutover: {e}")
                    self._emit(v, "cutover", "ok", replica=rid,
                               reason="replica died mid-commit; will "
                                      "boot from the registry")
                    continue
                # a worker RESTARTED between staging and this commit
                # lost its staged token: catch it up from the snapshot
                # path (one extra stage, off the request path) before
                # giving up on the whole candidate
                caught_up = False
                if v.path is not None:
                    try:
                        fresh = rep.stage(path=v.path)
                        fleet.commit_replica(rid, fresh,
                                             version=v.version,
                                             digest=v.digest)
                        per[rid] = fresh
                        caught_up = True
                    except Exception:
                        log.exception("catch-up re-stage of replica %s "
                                      "failed", rid)
                if not caught_up:
                    try:
                        fleet.undrain_replica(rid)
                    except Exception:
                        pass
                    self._emit(v, "cutover", "rejected", replica=rid,
                               reason=f"commit failed: {e}")
                    roll_back(f"commit failed on replica {rid} ({e})")
                    return v
            if self.chaos is not None:
                self.chaos("cutover", v)
            try:
                fleet.undrain_replica(rid)
            except Exception as e:
                # died between commit and undrain: the commit landed --
                # mark dead and keep rolling (a restart boots from the
                # registry, the post-promote reconcile catches an early
                # rebirth)
                log.exception("undrain of replica %s failed", rid)
                if not rep.alive():
                    fleet.mark_dead(rep,
                                    reason=f"died mid-undrain: {e}")
            self._emit(v, "cutover", "ok", replica=rid,
                       drained=drained)
            touched.append(rid)
        if not touched:
            self.registry.mark(v.version, "rejected")
            self._emit(v, "cutover", "rejected",
                       reason="no live replica accepted the candidate")
            return v
        self.registry.promote(v.version)
        # reconcile replicas that missed the roll: one that died
        # mid-roll and was RESTARTED by the supervisor before this
        # promote landed booted the registry's OLD version and would
        # silently serve it forever -- catch any such stragglers up
        # from the promoted snapshot (idempotent on a replica that
        # already booted the new version)
        if v.path is not None:
            for rid in fleet.replica_ids():
                rep = fleet._by_id(rid)
                if rid in touched or rep.state != "serving":
                    continue
                try:
                    fresh = rep.stage(path=v.path)
                    rep.commit(fresh, version=v.version,
                               digest=v.digest)
                    self._emit(v, "cutover", "ok", replica=rid,
                               reason="post-promote catch-up (replica "
                                      "missed the roll)")
                    touched.append(rid)
                except Exception:
                    log.exception("post-promote catch-up of replica %s "
                                  "failed (its next restart boots the "
                                  "promoted version)", rid)
        self._emit(v, "live", "ok", replicas=touched)
        if self.post_cutover_watch_s > 0:
            self._watch_until = self.clock() + self.post_cutover_watch_s
        return v

    # ----- rollback -------------------------------------------------------------- #
    def check_watch(self):
        """Inside the post-cutover watch window, a non-``ok`` health
        source (burning SLO, watchdog anomaly) triggers automatic
        rollback to the retained previous version.  No-op outside the
        window.  Returns the rolled-back-to version, or None."""
        if self._watch_until is None:
            return None
        if self.clock() >= self._watch_until:
            self._watch_until = None
            return None
        status, why = self._health()
        if status == "ok":
            return None
        self._watch_until = None
        return self.rollback(f"health went {status} inside the "
                             f"post-cutover watch window ({why})")

    def rollback(self, reason=None):
        """Pointer-swap back to the retained previous version: commit
        its STAGED handle (no re-quantize, no re-stage), swap the
        registry pointers durably, emit the durable rollback event.
        Returns the now-live (previous) version."""
        prev = self.registry.previous
        if prev is None or prev.handle is None:
            raise RuntimeError(
                "rollback without a retained previous version"
                + ("" if prev is None else
                   f" (v{prev.version} kept no staged buffers -- "
                   f"was this process restarted since the cutover?)"))
        self.engine.commit_staged(prev.handle, version=prev.version,
                                  digest=prev.digest)
        now_live, rolled = self.registry.rollback()
        self._emit(rolled if rolled is not None else now_live,
                   "rollback", "rolled_back", reason=reason,
                   rolled_back_to=now_live.version)
        return now_live
