"""Pytree / shape helpers shared across the framework.

Activities (layer inputs/outputs) are either a single ``jax.Array`` or a
nested tuple/list of arrays -- the TPU-native analogue of the reference's
``Activity = Tensor | Table`` (nn/abstractnn/Activity.scala).
"""

import jax
import jax.numpy as jnp


def spec_of(activity):
    """Abstract ShapeDtypeStruct pytree for a concrete (or abstract) activity."""
    return jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(jnp.shape(a), jnp.result_type(a)), activity
    )


def tree_add(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return jax.tree.map(jnp.add, a, b)


def tree_zeros_like(tree):
    return jax.tree.map(jnp.zeros_like, tree)


def tree_size(tree):
    """Total number of elements over all leaves."""
    return sum(x.size for x in jax.tree.leaves(tree))
