"""jax version compatibility seams.

The stack targets the current jax API; where an installed jax predates a
rename, the shim maps the new spelling onto the old one so the SAME call
sites run on both.  Keep this module dependency-light: it is imported
from inside step builders.
"""

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, **kw):
    """``jax.shard_map`` (new API) with fallback to
    ``jax.experimental.shard_map.shard_map`` (pre-0.5 jax), where the
    replication-checking flag was spelled ``check_rep`` instead of
    ``check_vma`` and partial-manual mode named the AUTO axes
    (``auto=``, the complement) instead of the MANUAL ones
    (``axis_names=``)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma, **kw)
    from jax.experimental.shard_map import shard_map as _shard_map
    manual = kw.pop("axis_names", None)
    if manual is not None:
        # old spelling: the axes NOT listed stay automatic (GSPMD)
        kw["auto"] = frozenset(mesh.axis_names) - frozenset(manual)
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma, **kw)


def axis_size(axis_name):
    """``jax.lax.axis_size`` (new API) with the classic
    ``psum(1, axis)`` fallback where the helper is absent."""
    import jax.lax as lax
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)
