"""Minimal xplane (jax.profiler trace) reader.

Used to cross-validate wall-clock step timings with the device plane's
own busy time (docs/performance.md: the chained-value-fetch clock needs
an independent witness through the tunneled transport).  Parses the
``*.xplane.pb`` files a ``jax.profiler.trace`` context writes, via the
TF-shipped proto when available, else a hand-rolled decoder for the few
XSpace fields the readers touch (the twin of the hand-rolled Event
encoder in ``visualization/tensorboard.py`` -- no TF dependency on the
read side either).

Both public readers (``device_busy``, ``op_breakdown``) return None --
never raise -- on a missing/empty/corrupt trace dir, so report tooling
can always call them unconditionally.
"""

import glob
import os
import re

_UNSET = object()
_xplane_pb2 = _UNSET  # import not attempted yet (None = unavailable)


def _load_proto():
    """The TF-shipped XSpace proto module, or None (cached)."""
    global _xplane_pb2
    if _xplane_pb2 is _UNSET:
        try:
            from tensorflow.tsl.profiler.protobuf import xplane_pb2
            _xplane_pb2 = xplane_pb2
        except Exception:
            try:
                from tensorflow.core.profiler.protobuf import xplane_pb2
                _xplane_pb2 = xplane_pb2
            except Exception:
                _xplane_pb2 = None
    return _xplane_pb2


# --------------------------------------------------------------------------- #
# Pure-python XSpace decoder (fallback when TF's proto is absent).  Only
# the fields the readers consume: XSpace.planes / XPlane.{name, lines,
# event_metadata} / XLine.{name, timestamp_ns, events} /
# XEvent.{metadata_id, offset_ps, duration_ps}.
# --------------------------------------------------------------------------- #


def _uvarint(data, off):
    shift = n = 0
    while True:
        b = data[off]
        off += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, off
        shift += 7


def _decode_fields(data):
    off = 0
    while off < len(data):
        key, off = _uvarint(data, off)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, off = _uvarint(data, off)
        elif wire == 1:
            val = data[off:off + 8]
            off += 8
        elif wire == 2:
            ln, off = _uvarint(data, off)
            val = data[off:off + ln]
            off += ln
        elif wire == 5:
            val = data[off:off + 4]
            off += 4
        else:
            return
        yield field, wire, val


class _PureEvent:
    __slots__ = ("metadata_id", "offset_ps", "duration_ps")

    def __init__(self, data):
        self.metadata_id = self.offset_ps = self.duration_ps = 0
        for f, w, v in _decode_fields(data):
            if w != 0:
                continue
            if f == 1:
                self.metadata_id = v
            elif f == 2:
                self.offset_ps = v
            elif f == 3:
                self.duration_ps = v


class _PureLine:
    __slots__ = ("name", "timestamp_ns", "events")

    def __init__(self, data):
        self.name, self.timestamp_ns, self.events = "", 0, []
        for f, w, v in _decode_fields(data):
            if f == 2 and w == 2:
                self.name = v.decode("utf-8", "replace")
            elif f == 3 and w == 0:
                self.timestamp_ns = v
            elif f == 4 and w == 2:
                self.events.append(_PureEvent(v))


class _PureEventMetadata:
    __slots__ = ("id", "name")

    def __init__(self, data):
        self.id, self.name = 0, ""
        for f, w, v in _decode_fields(data):
            if f == 1 and w == 0:
                self.id = v
            elif f == 2 and w == 2:
                self.name = v.decode("utf-8", "replace")


class _PurePlane:
    __slots__ = ("name", "lines", "event_metadata")

    def __init__(self, data):
        self.name, self.lines, self.event_metadata = "", [], {}
        for f, w, v in _decode_fields(data):
            if f == 2 and w == 2:
                self.name = v.decode("utf-8", "replace")
            elif f == 3 and w == 2:
                self.lines.append(_PureLine(v))
            elif f == 4 and w == 2:   # map<int64, XEventMetadata> entry
                key, meta = 0, None
                for f2, w2, v2 in _decode_fields(v):
                    if f2 == 1 and w2 == 0:
                        key = v2
                    elif f2 == 2 and w2 == 2:
                        meta = _PureEventMetadata(v2)
                if meta is not None:
                    self.event_metadata[key or meta.id] = meta


class _PureXSpace:
    __slots__ = ("planes",)

    def __init__(self, data):
        self.planes = [_PurePlane(v) for f, w, v in _decode_fields(data)
                       if f == 1 and w == 2]


def _parse_xspace(data):
    pb2 = _load_proto()
    if pb2 is not None:
        xs = pb2.XSpace()
        xs.ParseFromString(data)
        return xs
    return _PureXSpace(data)


def _iter_device_planes(trace_dir):
    """Yield every device (TPU/XLA) plane in the trace's xplane files.

    Yields nothing (so both public readers return None) for a None /
    nonexistent / empty trace dir; a corrupt xplane file is skipped
    rather than raised.
    """
    if not trace_dir or not os.path.isdir(str(trace_dir)):
        return
    for path in glob.glob(os.path.join(str(trace_dir), "**", "*.xplane.pb"),
                          recursive=True):
        try:
            with open(path, "rb") as f:
                xs = _parse_xspace(f.read())
        except Exception:
            continue   # partial/corrupt trace file: skip, never raise
        for plane in xs.planes:
            name = plane.name.lower()
            if "tpu" in name or "device" in name or "xla" in name:
                yield plane


def device_busy(trace_dir):
    """Largest device-plane span in the trace.

    Returns ``{"plane", "span_sec", "busy_event_sec"}`` for the device
    (TPU/XLA) plane with the longest span, or None when no device plane
    or proto support is available (e.g. CPU-only traces).
    """
    best = None
    for plane in _iter_device_planes(trace_dir):
        lo, hi, busiest = None, None, 0
        for line in plane.lines:
            # event offsets are relative to the LINE's timestamp;
            # align to absolute picoseconds before comparing lines
            base = line.timestamp_ns * 1000
            line_busy = 0
            for ev in line.events:
                start = base + ev.offset_ps
                end = start + ev.duration_ps
                lo = start if lo is None else min(lo, start)
                hi = end if hi is None else max(hi, end)
                line_busy += ev.duration_ps
            # lines nest hierarchically (modules > ops): summing
            # across lines double-counts, and async lines (e.g.
            # "Async XLA Ops") hold in-flight spans that overlap
            # compute -- so busy = the busiest synchronous line
            if "async" not in line.name.lower():
                busiest = max(busiest, line_busy)
        if hi is not None:
            rec = {"plane": plane.name,
                   "span_sec": (hi - lo) / 1e12,
                   "busy_event_sec": busiest / 1e12}
            if best is None or rec["span_sec"] > best["span_sec"]:
                best = rec
    return best


def op_breakdown(trace_dir, top=30):
    """Aggregate device-plane event time by op name and opcode category.

    The per-op HLO time accounting the perf docs cite: for the device
    plane's op-level line, sums event durations by name and returns
    ``{"plane", "total_sec", "categories": [...], "ops": [{"name",
    "sec", "pct", "count"}, ...]}`` with the top-N ops by total time, or
    None when no device plane / proto support exists.  Event names are
    resolved through the plane's metadata table (events carry metadata
    ids, not strings).
    """
    best = None
    for plane in _iter_device_planes(trace_dir):
        meta = {m_id: m.name for m_id, m in plane.event_metadata.items()}
        # the op-level accounting line is "XLA Ops" (serialized,
        # non-overlapping); fall back to the busiest line that is
        # not an async (in-flight, overlapping) line
        busiest_line, busiest = None, 0
        for line in plane.lines:
            if line.name == "XLA Ops":
                busiest_line = line
                break
            if "async" in line.name.lower():
                continue
            line_busy = sum(ev.duration_ps for ev in line.events)
            if line_busy > busiest:
                busiest, busiest_line = line_busy, line
        if busiest_line is None:
            continue
        by_op, by_cat = {}, {}
        for ev in busiest_line.events:
            op = meta.get(ev.metadata_id, str(ev.metadata_id))
            sec, cnt = by_op.get(op, (0, 0))
            by_op[op] = (sec + ev.duration_ps, cnt + 1)
            m = re.search(r"= \S+ ([a-z][a-z0-9_-]*)\(", op)
            cat = m.group(1) if m else op.split(".")[0].lstrip("%")
            sec, cnt = by_cat.get(cat, (0, 0))
            by_cat[cat] = (sec + ev.duration_ps, cnt + 1)
        total = sum(s for s, _ in by_op.values())
        if not total:
            continue
        ops = sorted(by_op.items(), key=lambda kv: -kv[1][0])[:top]
        cats = sorted(by_cat.items(), key=lambda kv: -kv[1][0])
        rec = {"plane": plane.name, "total_sec": total / 1e12,
               "categories": [{"name": cat, "sec": s / 1e12,
                               "pct": round(100.0 * s / total, 2),
                               "count": c} for cat, (s, c) in cats],
               "ops": [{"name": op, "sec": s / 1e12,
                        "pct": round(100.0 * s / total, 2), "count": c}
                       for op, (s, c) in ops]}
        if best is None or rec["total_sec"] > best["total_sec"]:
            best = rec
    return best
