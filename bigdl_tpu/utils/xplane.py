"""Minimal xplane (jax.profiler trace) reader.

Used to cross-validate wall-clock step timings with the device plane's
own busy time (docs/performance.md: the chained-value-fetch clock needs
an independent witness through the tunneled transport).  Parses the
``*.xplane.pb`` files a ``jax.profiler.trace`` context writes, via the
TF-shipped proto when available, else a hand-rolled decoder for the few
XSpace fields the readers touch (the twin of the hand-rolled Event
encoder in ``visualization/tensorboard.py`` -- no TF dependency on the
read side either).

All public readers (``device_busy``, ``op_breakdown``,
``device_attribution``) return None -- never raise -- on a
missing/empty/corrupt trace dir, so report tooling can always call
them unconditionally.
"""

import glob
import os
import re

_UNSET = object()
_xplane_pb2 = _UNSET  # import not attempted yet (None = unavailable)


def _load_proto():
    """The TF-shipped XSpace proto module, or None (cached)."""
    global _xplane_pb2
    if _xplane_pb2 is _UNSET:
        try:
            from tensorflow.tsl.profiler.protobuf import xplane_pb2
            _xplane_pb2 = xplane_pb2
        except Exception:
            try:
                from tensorflow.core.profiler.protobuf import xplane_pb2
                _xplane_pb2 = xplane_pb2
            except Exception:
                _xplane_pb2 = None
    return _xplane_pb2


# --------------------------------------------------------------------------- #
# Pure-python XSpace decoder (fallback when TF's proto is absent).  Only
# the fields the readers consume: XSpace.planes / XPlane.{name, lines,
# event_metadata} / XLine.{name, timestamp_ns, events} /
# XEvent.{metadata_id, offset_ps, duration_ps}.
# --------------------------------------------------------------------------- #


def _uvarint(data, off):
    shift = n = 0
    while True:
        b = data[off]
        off += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, off
        shift += 7


def _decode_fields(data):
    off = 0
    while off < len(data):
        key, off = _uvarint(data, off)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, off = _uvarint(data, off)
        elif wire == 1:
            val = data[off:off + 8]
            off += 8
        elif wire == 2:
            ln, off = _uvarint(data, off)
            val = data[off:off + ln]
            off += ln
        elif wire == 5:
            val = data[off:off + 4]
            off += 4
        else:
            return
        yield field, wire, val


class _PureEvent:
    __slots__ = ("metadata_id", "offset_ps", "duration_ps")

    def __init__(self, data):
        self.metadata_id = self.offset_ps = self.duration_ps = 0
        for f, w, v in _decode_fields(data):
            if w != 0:
                continue
            if f == 1:
                self.metadata_id = v
            elif f == 2:
                self.offset_ps = v
            elif f == 3:
                self.duration_ps = v


class _PureLine:
    __slots__ = ("name", "timestamp_ns", "events")

    def __init__(self, data):
        self.name, self.timestamp_ns, self.events = "", 0, []
        for f, w, v in _decode_fields(data):
            if f == 2 and w == 2:
                self.name = v.decode("utf-8", "replace")
            elif f == 3 and w == 0:
                self.timestamp_ns = v
            elif f == 4 and w == 2:
                self.events.append(_PureEvent(v))


class _PureEventMetadata:
    __slots__ = ("id", "name")

    def __init__(self, data):
        self.id, self.name = 0, ""
        for f, w, v in _decode_fields(data):
            if f == 1 and w == 0:
                self.id = v
            elif f == 2 and w == 2:
                self.name = v.decode("utf-8", "replace")


class _PurePlane:
    __slots__ = ("name", "lines", "event_metadata")

    def __init__(self, data):
        self.name, self.lines, self.event_metadata = "", [], {}
        for f, w, v in _decode_fields(data):
            if f == 2 and w == 2:
                self.name = v.decode("utf-8", "replace")
            elif f == 3 and w == 2:
                self.lines.append(_PureLine(v))
            elif f == 4 and w == 2:   # map<int64, XEventMetadata> entry
                key, meta = 0, None
                for f2, w2, v2 in _decode_fields(v):
                    if f2 == 1 and w2 == 0:
                        key = v2
                    elif f2 == 2 and w2 == 2:
                        meta = _PureEventMetadata(v2)
                if meta is not None:
                    self.event_metadata[key or meta.id] = meta


class _PureXSpace:
    __slots__ = ("planes",)

    def __init__(self, data):
        self.planes = [_PurePlane(v) for f, w, v in _decode_fields(data)
                       if f == 1 and w == 2]


def _parse_xspace(data):
    pb2 = _load_proto()
    if pb2 is not None:
        xs = pb2.XSpace()
        xs.ParseFromString(data)
        return xs
    return _PureXSpace(data)


def _iter_device_planes(trace_dir):
    """Yield every device (TPU/XLA) plane in the trace's xplane files.

    Yields nothing (so the public readers return None) for a None /
    nonexistent / empty trace dir; a corrupt xplane file is skipped
    rather than raised.  A list/tuple of already-parsed planes (from
    ``load_device_planes``) passes through unchanged, so one decode can
    feed all three readers.
    """
    if isinstance(trace_dir, (list, tuple)):
        yield from trace_dir
        return
    if not trace_dir or not os.path.isdir(str(trace_dir)):
        return
    for path in glob.glob(os.path.join(str(trace_dir), "**", "*.xplane.pb"),
                          recursive=True):
        try:
            with open(path, "rb") as f:
                xs = _parse_xspace(f.read())
        except Exception:
            continue   # partial/corrupt trace file: skip, never raise
        for plane in xs.planes:
            name = plane.name.lower()
            if "tpu" in name or "device" in name or "xla" in name:
                yield plane


def load_device_planes(trace_dir):
    """Decode the trace ONCE: returns the parsed device planes as a
    list that every reader (``device_busy`` / ``op_breakdown`` /
    ``device_attribution``) accepts in place of the directory -- report
    tooling that wants all three summaries pays one proto decode, not
    three."""
    return list(_iter_device_planes(trace_dir))


def device_busy(trace_dir):
    """Largest device-plane span in the trace.

    Returns ``{"plane", "span_sec", "busy_event_sec"}`` for the device
    (TPU/XLA) plane with the longest span, or None when no device plane
    or proto support is available (e.g. CPU-only traces).
    """
    best = None
    for plane in _iter_device_planes(trace_dir):
        lo, hi, busiest = None, None, 0
        for line in plane.lines:
            # event offsets are relative to the LINE's timestamp;
            # align to absolute picoseconds before comparing lines
            base = line.timestamp_ns * 1000
            line_busy = 0
            for ev in line.events:
                start = base + ev.offset_ps
                end = start + ev.duration_ps
                lo = start if lo is None else min(lo, start)
                hi = end if hi is None else max(hi, end)
                line_busy += ev.duration_ps
            # lines nest hierarchically (modules > ops): summing
            # across lines double-counts, and async lines (e.g.
            # "Async XLA Ops") hold in-flight spans that overlap
            # compute -- so busy = the busiest synchronous line
            if "async" not in line.name.lower():
                busiest = max(busiest, line_busy)
        if hi is not None:
            rec = {"plane": plane.name,
                   "span_sec": (hi - lo) / 1e12,
                   "busy_event_sec": busiest / 1e12}
            if best is None or rec["span_sec"] > best["span_sec"]:
                best = rec
    return best


#: HLO opcode categories that are cross-device communication, not local
#: compute (the attribution split ``device_attribution`` reports).
#: Start/done pairs cover the async-collective HLO spellings.
COLLECTIVE_CATEGORIES = frozenset({
    "all-reduce", "all-gather", "all-to-all", "reduce-scatter",
    "collective-permute", "collective-broadcast",
    "all-reduce-start", "all-reduce-done",
    "all-gather-start", "all-gather-done",
    "all-to-all-start", "all-to-all-done",
    "reduce-scatter-start", "reduce-scatter-done",
    "collective-permute-start", "collective-permute-done",
    "send", "recv", "send-done", "recv-done",
})


def _op_category(op_name):
    """HLO opcode category of an op name: ``"%all-reduce.9 = f32[...]
    all-reduce(%g)"`` -> ``"all-reduce"`` (falls back to the name stem
    for non-HLO event names)."""
    m = re.search(r"= \S+ ([a-z][a-z0-9_-]*)\(", op_name)
    return m.group(1) if m else op_name.split(".")[0].lstrip("%")


def _op_line(plane):
    """The plane's op-level accounting line: "XLA Ops" (serialized,
    non-overlapping) when present, else the busiest line that is not an
    async (in-flight, overlapping) line; None when the plane has no
    usable line."""
    busiest_line, busiest = None, 0
    for line in plane.lines:
        if line.name == "XLA Ops":
            return line
        if "async" in line.name.lower():
            continue
        line_busy = sum(ev.duration_ps for ev in line.events)
        if line_busy > busiest:
            busiest, busiest_line = line_busy, line
    return busiest_line


def op_breakdown(trace_dir, top=30):
    """Aggregate device-plane event time by op name and opcode category.

    The per-op HLO time accounting the perf docs cite: for the device
    plane's op-level line, sums event durations by name and returns
    ``{"plane", "total_sec", "categories": [...], "ops": [{"name",
    "sec", "pct", "count"}, ...]}`` with the top-N ops by total time, or
    None when no device plane / proto support exists.  Event names are
    resolved through the plane's metadata table (events carry metadata
    ids, not strings).
    """
    best = None
    for plane in _iter_device_planes(trace_dir):
        meta = {m_id: m.name for m_id, m in plane.event_metadata.items()}
        busiest_line = _op_line(plane)
        if busiest_line is None:
            continue
        by_op, by_cat = {}, {}
        for ev in busiest_line.events:
            op = meta.get(ev.metadata_id, str(ev.metadata_id))
            sec, cnt = by_op.get(op, (0, 0))
            by_op[op] = (sec + ev.duration_ps, cnt + 1)
            cat = _op_category(op)
            sec, cnt = by_cat.get(cat, (0, 0))
            by_cat[cat] = (sec + ev.duration_ps, cnt + 1)
        total = sum(s for s, _ in by_op.values())
        if not total:
            continue
        ops = sorted(by_op.items(), key=lambda kv: -kv[1][0])[:top]
        cats = sorted(by_cat.items(), key=lambda kv: -kv[1][0])
        rec = {"plane": plane.name, "total_sec": total / 1e12,
               "categories": [{"name": cat, "sec": s / 1e12,
                               "pct": round(100.0 * s / total, 2),
                               "count": c} for cat, (s, c) in cats],
               "ops": [{"name": op, "sec": s / 1e12,
                        "pct": round(100.0 * s / total, 2), "count": c}
                       for op, (s, c) in ops]}
        if best is None or rec["total_sec"] > best["total_sec"]:
            best = rec
    return best


def device_attribution(trace_dir, top=10):
    """Compute vs collective vs idle device-time attribution.

    Over the busiest device plane's op-level line (serialized,
    non-overlapping -- see ``_op_line``):

    - ``span_sec``: the line's envelope (first op start -> last op end);
    - ``busy_sec``: summed op durations, split into ``compute_sec`` and
      ``collective_sec`` by HLO opcode category
      (``COLLECTIVE_CATEGORIES``);
    - ``idle_sec`` = span - busy: time the device spent waiting (host
      dispatch gaps, input stalls) inside the traced window;
    - the ``*_fraction`` triple is each part over the span, so the
      three fractions sum to 1;
    - ``ops``: the top-N ops by device time, each tagged with its
      ``flavor`` (``"compute"`` | ``"collective"``).

    Returns None (never raises) when no device plane exists -- same
    contract as the other readers.
    """
    best = None
    for plane in _iter_device_planes(trace_dir):
        meta = {m_id: m.name for m_id, m in plane.event_metadata.items()}
        line = _op_line(plane)
        if line is None or not line.events:
            continue
        lo = hi = None
        busy = collective = 0
        by_op = {}
        for ev in line.events:
            start = ev.offset_ps
            end = start + ev.duration_ps
            lo = start if lo is None else min(lo, start)
            hi = end if hi is None else max(hi, end)
            busy += ev.duration_ps
            op = meta.get(ev.metadata_id, str(ev.metadata_id))
            is_coll = _op_category(op) in COLLECTIVE_CATEGORIES
            if is_coll:
                collective += ev.duration_ps
            sec, cnt, _ = by_op.get(op, (0, 0, is_coll))
            by_op[op] = (sec + ev.duration_ps, cnt + 1, is_coll)
        span = hi - lo
        if not busy or not span:
            continue
        # the "XLA Ops" line is serialized, but the busiest-line
        # FALLBACK can carry overlapping events: summed durations then
        # exceed the envelope.  Widen the span to the busy total so the
        # three fractions still partition it (idle reads 0, honestly:
        # overlap means the device was never observed waiting)
        span = max(span, busy)
        compute = busy - collective
        idle = span - busy
        ops = sorted(by_op.items(), key=lambda kv: -kv[1][0])[:top]
        rec = {
            "plane": plane.name,
            "span_sec": span / 1e12,
            "busy_sec": busy / 1e12,
            "compute_sec": compute / 1e12,
            "collective_sec": collective / 1e12,
            "idle_sec": idle / 1e12,
            "compute_fraction": round(compute / span, 4),
            "collective_fraction": round(collective / span, 4),
            "idle_fraction": round(idle / span, 4),
            "ops": [{"name": op, "sec": s / 1e12,
                     "pct": round(100.0 * s / busy, 2), "count": c,
                     "flavor": "collective" if coll else "compute"}
                    for op, (s, c, coll) in ops],
        }
        if best is None or rec["busy_sec"] > best["busy_sec"]:
            best = rec
    return best
