"""Minimal xplane (jax.profiler trace) reader.

Used to cross-validate wall-clock step timings with the device plane's
own busy time (docs/performance.md: the chained-value-fetch clock needs
an independent witness through the tunneled transport).  Parses the
``*.xplane.pb`` files a ``jax.profiler.trace`` context writes, via the
TF-shipped proto (no tensorboard plugin needed).
"""

import glob
import os
import re


def _iter_device_planes(trace_dir):
    """Yield every device (TPU/XLA) plane in the trace's xplane files.

    Yields nothing when the TF proto is unavailable (e.g. CPU-only
    environments) -- both public readers then return None.
    """
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except Exception:
        try:
            from tensorflow.core.profiler.protobuf import xplane_pb2
        except Exception:
            return
    for path in glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                          recursive=True):
        xs = xplane_pb2.XSpace()
        with open(path, "rb") as f:
            xs.ParseFromString(f.read())
        for plane in xs.planes:
            name = plane.name.lower()
            if "tpu" in name or "device" in name or "xla" in name:
                yield plane


def device_busy(trace_dir):
    """Largest device-plane span in the trace.

    Returns ``{"plane", "span_sec", "busy_event_sec"}`` for the device
    (TPU/XLA) plane with the longest span, or None when no device plane
    or proto support is available (e.g. CPU-only traces).
    """
    best = None
    for plane in _iter_device_planes(trace_dir):
        lo, hi, busiest = None, None, 0
        for line in plane.lines:
            # event offsets are relative to the LINE's timestamp;
            # align to absolute picoseconds before comparing lines
            base = line.timestamp_ns * 1000
            line_busy = 0
            for ev in line.events:
                start = base + ev.offset_ps
                end = start + ev.duration_ps
                lo = start if lo is None else min(lo, start)
                hi = end if hi is None else max(hi, end)
                line_busy += ev.duration_ps
            # lines nest hierarchically (modules > ops): summing
            # across lines double-counts, and async lines (e.g.
            # "Async XLA Ops") hold in-flight spans that overlap
            # compute -- so busy = the busiest synchronous line
            if "async" not in line.name.lower():
                busiest = max(busiest, line_busy)
        if hi is not None:
            rec = {"plane": plane.name,
                   "span_sec": (hi - lo) / 1e12,
                   "busy_event_sec": busiest / 1e12}
            if best is None or rec["span_sec"] > best["span_sec"]:
                best = rec
    return best


def op_breakdown(trace_dir, top=30):
    """Aggregate device-plane event time by op name and opcode category.

    The per-op HLO time accounting the perf docs cite: for the device
    plane's op-level line, sums event durations by name and returns
    ``{"plane", "total_sec", "categories": [...], "ops": [{"name",
    "sec", "pct", "count"}, ...]}`` with the top-N ops by total time, or
    None when no device plane / proto support exists.  Event names are
    resolved through the plane's metadata table (events carry metadata
    ids, not strings).
    """
    best = None
    for plane in _iter_device_planes(trace_dir):
        meta = {m_id: m.name for m_id, m in plane.event_metadata.items()}
        # the op-level accounting line is "XLA Ops" (serialized,
        # non-overlapping); fall back to the busiest line that is
        # not an async (in-flight, overlapping) line
        busiest_line, busiest = None, 0
        for line in plane.lines:
            if line.name == "XLA Ops":
                busiest_line = line
                break
            if "async" in line.name.lower():
                continue
            line_busy = sum(ev.duration_ps for ev in line.events)
            if line_busy > busiest:
                busiest, busiest_line = line_busy, line
        if busiest_line is None:
            continue
        by_op, by_cat = {}, {}
        for ev in busiest_line.events:
            op = meta.get(ev.metadata_id, str(ev.metadata_id))
            sec, cnt = by_op.get(op, (0, 0))
            by_op[op] = (sec + ev.duration_ps, cnt + 1)
            m = re.search(r"= \S+ ([a-z][a-z0-9_-]*)\(", op)
            cat = m.group(1) if m else op.split(".")[0].lstrip("%")
            sec, cnt = by_cat.get(cat, (0, 0))
            by_cat[cat] = (sec + ev.duration_ps, cnt + 1)
        total = sum(s for s, _ in by_op.values())
        if not total:
            continue
        ops = sorted(by_op.items(), key=lambda kv: -kv[1][0])[:top]
        cats = sorted(by_cat.items(), key=lambda kv: -kv[1][0])
        rec = {"plane": plane.name, "total_sec": total / 1e12,
               "categories": [{"name": cat, "sec": s / 1e12,
                               "pct": round(100.0 * s / total, 2),
                               "count": c} for cat, (s, c) in cats],
               "ops": [{"name": op, "sec": s / 1e12,
                        "pct": round(100.0 * s / total, 2), "count": c}
                       for op, (s, c) in ops]}
        if best is None or rec["total_sec"] > best["total_sec"]:
            best = rec
    return best
