"""Minimal xplane (jax.profiler trace) reader.

Used to cross-validate wall-clock step timings with the device plane's
own busy time (docs/performance.md: the chained-value-fetch clock needs
an independent witness through the tunneled transport).  Parses the
``*.xplane.pb`` files a ``jax.profiler.trace`` context writes, via the
TF-shipped proto (no tensorboard plugin needed).
"""

import glob
import os


def device_busy(trace_dir):
    """Largest device-plane span in the trace.

    Returns ``{"plane", "span_sec", "busy_event_sec"}`` for the device
    (TPU/XLA) plane with the longest span, or None when no device plane
    or proto support is available (e.g. CPU-only traces).
    """
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except Exception:
        try:
            from tensorflow.core.profiler.protobuf import xplane_pb2
        except Exception:
            return None
    best = None
    for path in glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                          recursive=True):
        xs = xplane_pb2.XSpace()
        with open(path, "rb") as f:
            xs.ParseFromString(f.read())
        for plane in xs.planes:
            name = plane.name.lower()
            if not ("tpu" in name or "device" in name or "xla" in name):
                continue
            lo, hi, busiest = None, None, 0
            for line in plane.lines:
                # event offsets are relative to the LINE's timestamp;
                # align to absolute picoseconds before comparing lines
                base = line.timestamp_ns * 1000
                line_busy = 0
                for ev in line.events:
                    start = base + ev.offset_ps
                    end = start + ev.duration_ps
                    lo = start if lo is None else min(lo, start)
                    hi = end if hi is None else max(hi, end)
                    line_busy += ev.duration_ps
                # lines nest hierarchically (modules > ops): summing
                # across lines double-counts, so busy = the busiest line
                busiest = max(busiest, line_busy)
            if hi is not None:
                rec = {"plane": plane.name,
                       "span_sec": (hi - lo) / 1e12,
                       "busy_event_sec": busiest / 1e12}
                if best is None or rec["span_sec"] > best["span_sec"]:
                    best = rec
    return best
