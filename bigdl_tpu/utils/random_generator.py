"""Deterministic RNG for reproducible init across hosts.

The reference ships its own Mersenne-Twister so every node derives identical
weights from a seed (utils/RandomGenerator.scala:23,56,116).  On TPU the same
guarantee comes for free from JAX's counter-based threefry PRNG: every host
that calls ``RNG.set_seed(s)`` and then draws the same sequence of keys gets
bitwise-identical results, with no communication.
"""

import threading

import jax


class RandomGenerator:
    """A splittable PRNG stream with global-seed semantics.

    ``set_seed`` resets the stream; ``next_key`` returns a fresh ``jax.random``
    key, advancing the stream.  Thread-safe (the reference keeps a thread-local
    generator; a lock is simpler and the facade is not hot-path).
    """

    def __init__(self, seed: int = 1):
        self._lock = threading.Lock()
        # LAZY: creating a jax key initialises the XLA backend, which must
        # not happen at import time (it would break
        # jax.distributed.initialize in multi-host processes)
        self._seed = int(seed)
        self._key = None

    def set_seed(self, seed: int) -> "RandomGenerator":
        with self._lock:
            self._seed = int(seed)
            self._key = jax.random.key(self._seed)
        return self

    def get_seed(self) -> int:
        return self._seed

    def next_key(self):
        with self._lock:
            if self._key is None:
                self._key = jax.random.key(self._seed)
            self._key, sub = jax.random.split(self._key)
            return sub

    def get_state(self):
        """Serializable stream position (checkpoints carry it so a resumed
        run draws the same key sequence as an uninterrupted one)."""
        import numpy as np
        with self._lock:
            if self._key is None:
                self._key = jax.random.key(self._seed)
            return np.asarray(jax.random.key_data(self._key))

    def set_state(self, data) -> "RandomGenerator":
        with self._lock:
            self._key = jax.random.wrap_key_data(jax.numpy.asarray(data))
        return self

    def uniform(self, shape, low=0.0, high=1.0, dtype="float32"):
        return jax.random.uniform(
            self.next_key(), shape, minval=low, maxval=high, dtype=dtype
        )

    def normal(self, shape, mean=0.0, stdv=1.0, dtype="float32"):
        return mean + stdv * jax.random.normal(self.next_key(), shape, dtype=dtype)


#: Global generator, mirroring ``RandomGenerator.RNG`` in the reference.
RNG = RandomGenerator()
