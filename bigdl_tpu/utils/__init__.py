from bigdl_tpu.utils.engine import Engine
from bigdl_tpu.utils.random_generator import RNG, RandomGenerator
from bigdl_tpu.utils.shape import spec_of, tree_add, tree_zeros_like
