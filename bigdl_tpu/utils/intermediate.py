"""Backend-neutral IR: the engine seam.

Reference: utils/intermediate/IRElement.scala:42-104 (IRElement/IROperator
case classes), IRGraph.scala (an AbstractModule that lazily builds a
concrete graph), IRConverter.scala:61-107 (toDnnGraph/toBlasGraph) — the
pluggable-engine seam where the reference swaps MklBlas for MklDnn
(SURVEY.md section 1, "key architectural fact").

TPU-native: the third engine the survey calls for.  ``to_ir`` lifts a
module tree into IRElements; ``IRGraph.to_xla`` lowers the IR back to
modules and AOT-compiles one fused XLA executable
(jit(...).lower().compile() — the analogue of DnnGraph.compile(phase),
nn/mkldnn/DnnGraph.scala:309).  Because every layer already lowers through
jnp/lax there is exactly one numeric backend; the IR's value is (a) a
stable describe/serialize surface and (b) the place a future engine
(e.g. a pallas-specialised layer set) plugs in, mirroring IRConverter.
"""

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class IRElement:
    """One node (reference: IRElement.scala:42)."""

    name: str
    op: str                                  # reference: IROperator subtype
    attrs: Dict[str, Any] = field(default_factory=dict)
    inputs: List[str] = field(default_factory=list)


@dataclass
class IRGraph:
    """Engine-neutral graph (reference: IRGraph.scala).

    ``dag=True`` marks the general DAG form (produced from nn.Graph);
    ``dag=False`` is the legacy chain/Concat form.
    """

    elements: List[IRElement]
    input_names: List[str]
    output_names: List[str]
    dag: bool = False

    def to_xla(self, input_spec, sample_input=None):
        """Lower to an AOT-compiled XLA executable
        (reference: IRConverter.toDnnGraph + DnnGraph.compile)."""
        import jax

        module = ir_to_module(self)
        module.build(input_spec)
        params, state = module._params, module._state

        def fwd(p, s, x):
            y, _ = module.apply(p, s, x, training=False, rng=None)
            return y

        compiled = jax.jit(fwd).lower(params, state, input_spec).compile()
        return module, compiled, (params, state)


_IR_ATTR_KEYS = {
    "Linear": ["input_size", "output_size", "with_bias"],
    "SpatialConvolution": ["n_input_plane", "n_output_plane", "kernel",
                           "stride", "pad", "n_group", "with_bias"],
    "SpatialMaxPooling": ["kernel", "stride", "pad", "ceil_mode"],
    "SpatialAveragePooling": ["kernel", "stride", "pad", "ceil_mode"],
    "BatchNormalization": ["n_output", "eps", "momentum", "affine"],
    "SpatialBatchNormalization": ["n_output", "eps", "momentum", "affine"],
    "Dropout": ["p"],
    "Reshape": ["size"],
    "LookupTable": ["n_index", "n_output"],
    "SpatialCrossMapLRN": ["size", "alpha", "beta", "k"],
    "Concat": ["dimension"],
    "JoinTable": ["dimension"],
}


def to_ir(module, prefix="") -> IRGraph:
    """Module tree -> IRGraph (reference: BlasToIR mapper,
    ReflectionUtils-driven in the reference; explicit attr tables here).

    Chains (Sequential/Concat) produce the legacy chain form; nn.Graph
    produces the general DAG form (round-2 VERDICT: branched graphs could
    not round-trip the IR)."""
    import bigdl_tpu.nn as nn

    elements: List[IRElement] = []

    def leaf_attrs(mod):
        cls = type(mod).__name__
        attrs = {}
        for key in _IR_ATTR_KEYS.get(cls, []):
            if hasattr(mod, key):
                attrs[key] = getattr(mod, key)
        return attrs

    def walk(mod, prefix, input_name):
        cls = type(mod).__name__
        my_name = f"{prefix}{mod.name}"
        if isinstance(mod, nn.Graph):
            return walk_graph(mod, f"{my_name}/", [input_name])
        if isinstance(mod, nn.Sequential):
            cur = input_name
            for i, child in enumerate(mod.modules):
                cur = walk(child, f"{my_name}/", cur)
            return cur
        if isinstance(mod, nn.Concat):
            branch_outs = [walk(child, f"{my_name}/{i}/", input_name)
                           for i, child in enumerate(mod.modules)]
            elements.append(IRElement(my_name, "Concat",
                                      {"dimension": mod.dimension,
                                       "_input": input_name},
                                      branch_outs))
            return my_name
        attrs = leaf_attrs(mod)
        elements.append(IRElement(my_name, cls, attrs, [input_name]))
        return my_name

    def walk_graph(g, prefix, outer_inputs):
        if len(g.input_nodes) != len(outer_inputs):
            raise NotImplementedError(
                "nested multi-input graphs need matching outer inputs")
        name_of = {}
        for node, outer in zip(g.input_nodes, outer_inputs):
            name_of[id(node)] = outer
        for i, node in enumerate(g._topo):
            if node.module is None:
                continue
            parents = [name_of[id(p)] for p in node.inputs]
            mod = node.module
            if isinstance(mod, (nn.Sequential, nn.Concat, nn.Graph)) \
                    and len(parents) == 1:
                name_of[id(node)] = walk(mod, prefix, parents[0])
                continue
            my_name = f"{prefix}{mod.name}#{i}"
            elements.append(IRElement(my_name, type(mod).__name__,
                                      leaf_attrs(mod), parents))
            name_of[id(node)] = my_name
        outs = [name_of[id(n)] for n in g.output_nodes]
        if len(outs) != 1:
            raise NotImplementedError("single-output IR graphs only")
        return outs[0]

    import bigdl_tpu.nn as _nn

    if isinstance(module, _nn.Graph):
        in_names = [f"input{i}" for i in range(len(module.input_nodes))]
        out = walk_graph(module, prefix, in_names)
        return IRGraph(elements, in_names, [out], dag=True)
    out = walk(module, prefix, "input")
    return IRGraph(elements, ["input"], [out])


class Lowering:
    """One engine's IR -> module mapping (reference: the IRToBlas/IRToDnn
    mapper pair selected inside IRConverter.scala:61-107).  Subclass and
    override :meth:`module_of` entries to plug a new engine in at exactly
    this seam — the survey's "the TPU build adds a third engine at
    exactly these seams" note."""

    name = "xla"

    def module_of(self, e: IRElement, nn):
        """IRElement -> concrete module (leaf ops only)."""
        cls = e.op
        a = e.attrs
        if cls == "Linear":
            return nn.Linear(a.get("input_size"), a.get("output_size"),
                             with_bias=a.get("with_bias", True))
        if cls == "SpatialConvolution":
            kh, kw = a["kernel"]
            sh, sw = a["stride"]
            ph, pw = a["pad"]
            return nn.SpatialConvolution(
                a["n_input_plane"], a["n_output_plane"], kw, kh, sw, sh,
                pw, ph, n_group=a.get("n_group", 1),
                with_bias=a.get("with_bias", True))
        if cls in ("SpatialMaxPooling", "SpatialAveragePooling"):
            kh, kw = a["kernel"]
            sh, sw = a["stride"]
            ph, pw = a["pad"]
            m = getattr(nn, cls)(kw, kh, sw, sh, pw, ph)
            if a.get("ceil_mode"):
                m.ceil()
            return m
        if cls in ("BatchNormalization", "SpatialBatchNormalization"):
            return getattr(nn, cls)(a["n_output"], a.get("eps", 1e-5),
                                    a.get("momentum", 0.1),
                                    affine=a.get("affine", True))
        if cls == "Dropout":
            return nn.Dropout(a.get("p", 0.5))
        if cls == "Reshape":
            return nn.Reshape(tuple(a["size"]))
        if cls == "LookupTable":
            return nn.LookupTable(a["n_index"], a["n_output"])
        if cls == "SpatialCrossMapLRN":
            return nn.SpatialCrossMapLRN(a["size"], a["alpha"], a["beta"],
                                         a["k"])
        if cls == "JoinTable":
            return nn.JoinTable(a["dimension"])
        if hasattr(nn, cls):
            return getattr(nn, cls)()          # parameter-free layer
        raise NotImplementedError(f"IR op {cls} ({self.name} engine)")

    def finalize(self, module):
        """Post-lowering rewrite hook (e.g. quantization)."""
        return module

    def lower(self, graph: IRGraph):
        """IRGraph -> module tree (reference: IRConverter.toDnnGraph /
        toBlasGraph)."""
        import bigdl_tpu.nn as nn

        producers = {e.name: e for e in graph.elements}

        def build_node(e: IRElement):
            if e.op == "Concat":
                cat = nn.Concat(e.attrs.get("dimension", -1))
                for src in e.inputs:
                    cat.add(build_chain(src, stop=e.attrs["_input"]))
                return cat
            return self.module_of(e, nn)

        def build_chain(output_name, stop="input"):
            """Chain ending at output_name, walking back to ``stop`` ->
            Sequential.  Concat nodes jump back through their feed."""
            chain = []
            cur = output_name
            while cur != stop and cur in producers:
                e = producers[cur]
                chain.append(e)
                cur = e.attrs["_input"] if e.op == "Concat" \
                    else e.inputs[0]
            chain.reverse()
            seq = nn.Sequential()
            for e in chain:
                seq.add(build_node(e))
            return seq

        assert len(graph.output_names) == 1, "single-output IR graphs only"
        if graph.dag:
            from bigdl_tpu.nn.graph import Input, Node

            node_of = {}
            for name in graph.input_names:
                node_of[name] = Input()
            for e in graph.elements:        # already topologically ordered
                if e.op == "Concat":
                    mod = nn.JoinTable(e.attrs.get("dimension", -1))
                else:
                    mod = build_node(e)
                node_of[e.name] = Node(mod, [node_of[p] for p in e.inputs])
            out = nn.Graph([node_of[n] for n in graph.input_names],
                           [node_of[graph.output_names[0]]])
        else:
            out = build_chain(graph.output_names[0])
        return self.finalize(out)


class QuantizedLowering(Lowering):
    """Int8 engine: float lowering + the Quantizer rewrite (reference:
    ConversionUtils.getInt8ModelIfNeeded -> nn.quantized.Quantization;
    here nn/quantized.py's MXU int8 modules)."""

    name = "quantized"

    def finalize(self, module):
        # the rewrite happens after weights are carried over -- convert()
        # calls finalize_built on the BUILT module instead
        return module

    def finalize_built(self, module):
        from bigdl_tpu.nn.quantized import quantize
        return quantize(module)


ENGINES: Dict[str, Lowering] = {
    "xla": Lowering(),
    "quantized": QuantizedLowering(),
}


def ir_to_module(graph: IRGraph, engine: str = "xla"):
    """IRGraph -> module tree through the selected engine's lowering
    (reference: IRToBlas / IRToDnn mappers)."""
    return ENGINES[engine].lower(graph)


def convert(model, engine: Optional[str] = None, input_spec=None):
    """``ConversionUtils.convert`` analogue (reference:
    utils/intermediate/ConversionUtils.scala:37-50): when the configured
    engine is not the direct one, lift the model to IR, lower it through
    the engine's mapping, and carry the built parameters over.  The
    training loops call this at model-init time, so setting
    ``BIGDL_ENGINE_TYPE=ir`` routes training through the IR seam and
    ``BIGDL_ENGINE_TYPE=ir-quantized`` through the int8 engine.
    """
    from bigdl_tpu.utils.config import engine_type

    engine = engine or engine_type()
    if engine in ("xla", "direct", "", None):
        return model                       # the modules ARE the xla engine
    if engine == "ir":
        lowering_name = "xla"
    elif engine.startswith("ir-"):
        lowering_name = engine[3:]
    else:
        raise ValueError(f"unknown engine type {engine!r} "
                         f"(expected xla | ir | ir-quantized)")
    if lowering_name not in ENGINES:
        raise ValueError(f"unknown IR engine {lowering_name!r} "
                         f"(registered: {sorted(ENGINES)})")
    lowering = ENGINES[lowering_name]

    import jax

    ir = to_ir(model)
    new = lowering.lower(ir)
    if model.is_built():
        spec = input_spec if input_spec is not None \
            else getattr(model, "_build_spec", None)
        if spec is None:
            raise ValueError("converting a built model needs input_spec")
        new.build(spec)
        old_p = jax.tree.leaves(model._params)
        new_p, treedef = jax.tree.flatten(new._params)
        if len(old_p) != len(new_p) or any(
                a.shape != b.shape for a, b in zip(old_p, new_p)):
            raise ValueError(
                "IR conversion changed the parameter structure; cannot "
                "carry weights over")
        new._params = jax.tree.unflatten(treedef, old_p)
        old_s = jax.tree.leaves(model._state)
        new_s, sdef = jax.tree.flatten(new._state)
        if len(old_s) != len(new_s) or any(
                getattr(a, "shape", None) != getattr(b, "shape", None)
                for a, b in zip(old_s, new_s)):
            raise ValueError(
                "IR conversion changed the state structure; cannot carry "
                "state (e.g. BN running stats) over")
        new._state = jax.tree.unflatten(sdef, old_s)
        if hasattr(lowering, "finalize_built"):
            new = lowering.finalize_built(new)
    elif hasattr(lowering, "finalize_built"):
        raise ValueError(
            f"the {lowering.name!r} engine rewrites a BUILT model "
            "(weights are required); build the model first")
    if not model.train_mode:
        new.evaluate()
    return new
