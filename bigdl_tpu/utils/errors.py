"""Deterministic configuration/capability errors.

The failure-retry loop (BaseOptimizer.optimize, reference retryNum
semantics) restores the last checkpoint and retries on RUNTIME failures;
these two classes mark errors that are deterministic functions of the
configuration -- retrying would replay the identical failure after
burning a restore cycle, so the loop re-raises them immediately.  They
subclass the builtin types the call sites historically raised, so
callers matching ValueError/NotImplementedError keep working.
"""


class ConfigurationError(ValueError):
    """A setting that can never work (bad name, uncovered subtree, ...)."""


class UnsupportedFeatureError(NotImplementedError):
    """A valid-looking combination this engine deliberately refuses."""


class TrainingHaltedError(RuntimeError):
    """A health watchdog's ``halt`` policy stopped the run
    (observability/health.py).  Deliberately NOT retried by the
    failure-retry loop: restoring a checkpoint and replaying the same
    batches reproduces the same numerics blow-up, burning retry cycles
    while destroying the incident evidence window."""


class CheckpointCorruptionError(RuntimeError):
    """A checkpoint directory EXISTS but every snapshot in it failed
    integrity verification (truncated write, digest mismatch) and was
    quarantined.  Distinct from "nothing to resume": silently starting
    fresh here would throw away a run the operator believes is
    recoverable.  The message lists the quarantined files
    (docs/robustness.md)."""
