"""Compiled-step HLO audit: what the program we hand XLA actually says.

The step-time campaign (ROADMAP item 1) needs the compiled train step to
be AUDITABLE, not just fast-feeling: an undonated parameter plane
silently doubles peak HBM, an fp32 matmul in a step that claims bf16
halves MXU throughput, and an unexpected collective is wire time nobody
budgeted.  All three are visible in the program text, so this module
extracts them:

- ``lowering_summary(lowered, args)`` -- parsed from the StableHLO
  LOWERING text (``lowered.as_text()``): per-plane buffer-donation
  markers (``tf.aliasing_output`` / ``jax.buffer_donor`` on the entry
  arguments), dot/conv result dtypes, and collective-op counts.  No
  backend compile, so ``StepTelemetry.attach_cost`` can stamp this on
  every run header for free (the "Compiled step" section of
  tools/obs_report.py).

- ``compiled_summary(compiled, args)`` -- parsed from the OPTIMIZED HLO
  (``compiled.as_text()``): the authoritative ``input_output_alias``
  table (which donations XLA actually honored), post-fusion dot/conv
  dtypes, collective counts and the fusion count.  This is what the
  lint-style gate (``tools/hlo_audit.py``) judges: it exits nonzero
  when a large param/opt-state leaf is undonated.

Both summaries share one coverage schema (``donation`` below); the
``source`` field says which text produced it.  Entry parameters
correspond 1:1, in order, to the flattened example-argument leaves --
the same flatten order ``jax.tree.flatten`` uses -- which is how a
parameter index maps back to a labeled plane and a tree path.

Schema (docs/observability.md, "Compiled step audit")::

    {"source": "lowering" | "compiled",
     "donation": {label: {"leaves", "bytes", "donated_leaves",
                          "donated_bytes", "undonated": [{path, bytes,
                          dtype}, ...]}},
     "dot_conv_dtypes": {"dot": {dtype: count}, "conv": {dtype: count}},
     "collectives": {op: count},          # only ops that appear
     "fusions": int,                      # compiled source only
     "memory": {"argument_bytes", "output_bytes", "temp_bytes",
                "alias_bytes", "generated_code_bytes",
                "peak_bytes"},            # compiled source only, when
                                          # the backend reports it
    }

``memory`` is the executable's compiled-program memory budget
(``compiled.memory_analysis()``, normalized by
``memory_analysis_summary`` below): what the program will ask the
allocator for BEFORE it runs -- the static side of the live
``MemoryLedger`` (observability/memory.py).

No jax import at module top: the parsers are pure text -> dict, so
tools can spec-load this file the way obs_report loads xplane.py.
"""

import math
import re

#: stablehlo collective ops (lowering text) -> canonical names
_STABLEHLO_COLLECTIVES = {
    "stablehlo.all_reduce": "all_reduce",
    "stablehlo.all_gather": "all_gather",
    "stablehlo.reduce_scatter": "reduce_scatter",
    "stablehlo.all_to_all": "all_to_all",
    "stablehlo.collective_permute": "collective_permute",
}

#: optimized-HLO collective op spellings (incl. async -start forms)
_HLO_COLLECTIVES = {
    "all-reduce": "all_reduce",
    "all-reduce-start": "all_reduce",
    "all-gather": "all_gather",
    "all-gather-start": "all_gather",
    "reduce-scatter": "reduce_scatter",
    "all-to-all": "all_to_all",
    "collective-permute": "collective_permute",
    "collective-permute-start": "collective_permute",
}


def arg_entries(example_args, arg_labels=None):
    """Flatten the step's example arguments into the entry-parameter
    view: ``[{label, path, shape, dtype, bytes}]`` in jax flatten order
    (= HLO entry parameter order).  ``arg_labels`` names the top-level
    positional args (``("params", "mstate", ...)``); unnamed tails get
    ``arg{i}``."""
    from jax.tree_util import keystr, tree_flatten_with_path

    labels = list(arg_labels or ())
    out = []
    for i, arg in enumerate(example_args):
        label = labels[i] if i < len(labels) else f"arg{i}"
        leaves, _ = tree_flatten_with_path(arg)
        for path, leaf in leaves:
            shape = tuple(getattr(leaf, "shape", ()))
            dtype = getattr(leaf, "dtype", None)
            try:
                nbytes = int(math.prod(shape)) * dtype.itemsize
            except Exception:
                nbytes = None
            out.append({
                "label": label,
                "path": label + keystr(path),
                "shape": shape,
                "dtype": str(dtype) if dtype is not None else None,
                "bytes": nbytes,
            })
    return out


# --------------------------------------------------------------------- #
# text parsers
# --------------------------------------------------------------------- #

def _main_signature(text):
    """The ``func.func public @main(...)`` argument region of an MLIR
    lowering (one printer line), or None."""
    m = re.search(r"func\.func public @main\((.*)$", text, re.MULTILINE)
    return m.group(1) if m else None


def donated_params_from_lowering(text):
    """Entry-parameter indices carrying a donation marker in the
    lowering text.  ``tf.aliasing_output`` = donation already resolved
    to an output alias; ``jax.buffer_donor`` = donated, aliasing left to
    the compiler (the shard_map path) -- both count as donated at the
    program level."""
    sig = _main_signature(text)
    if sig is None:
        return set()
    # split the signature at each %argN; attributes for arg N live
    # between its marker and the next one (or the result arrow)
    marks = [(int(m.group(1)), m.start())
             for m in re.finditer(r"%arg(\d+)\s*:", sig)]
    donated = set()
    for k, (idx, start) in enumerate(marks):
        end = marks[k + 1][1] if k + 1 < len(marks) else len(sig)
        seg = sig[start:end]
        if "tf.aliasing_output" in seg or "jax.buffer_donor" in seg:
            donated.add(idx)
    return donated


def aliased_params_from_compiled(text):
    """Entry-parameter indices in the optimized HLO's authoritative
    ``input_output_alias={ {out}: (param, {index}, kind), ... }``
    table."""
    i = text.find("input_output_alias={")
    if i < 0:
        return set()
    start = text.index("{", i + len("input_output_alias="))
    depth, j = 0, start
    while j < len(text):
        if text[j] == "{":
            depth += 1
        elif text[j] == "}":
            depth -= 1
            if depth == 0:
                break
        j += 1
    block = text[start:j + 1]
    return {int(m.group(1)) for m in re.finditer(r"\((\d+),", block)}


def dot_conv_from_lowering(text):
    """{op: {result dtype: count}} for stablehlo dot_general /
    convolution ops -- the dtype the PROGRAM requests of the matmul
    path, before any backend rewrite (an f32 here, in a step that
    claims bf16, is a precision-policy bug, not a backend quirk)."""
    out = {}
    for op, key in (("stablehlo.dot_general", "dot"),
                    ("stablehlo.convolution", "conv")):
        counts = {}
        for ln in text.splitlines():
            if op not in ln:
                continue
            # result type is the tensor element dtype AFTER the dims
            # ("tensor<16x32xbf16>" -> "bf16"; rank-0 "tensor<f32>")
            m = re.search(r"->\s*tensor<[0-9x]*([a-z][a-z0-9]*)>\s*$",
                          ln.strip())
            dt = m.group(1) if m else "?"
            counts[dt] = counts.get(dt, 0) + 1
        if counts:
            out[key] = counts
    return out


def dot_conv_from_compiled(text):
    """{op: {result dtype: count}} for dot/convolution ops in the
    optimized HLO (post-layout, post-rewrite -- what actually runs)."""
    out = {}
    for pat, key in ((r"= ([a-z][a-z0-9]*)\[[^\]]*\][^ ]* dot\(", "dot"),
                     (r"= ([a-z][a-z0-9]*)\[[^\]]*\][^ ]* convolution\(",
                      "conv")):
        counts = {}
        for m in re.finditer(pat, text):
            dt = m.group(1)
            counts[dt] = counts.get(dt, 0) + 1
        if counts:
            out[key] = counts
    return out


def collectives_from_lowering(text):
    counts = {}
    for op, name in _STABLEHLO_COLLECTIVES.items():
        # the MLIR printer emits plain ops as `stablehlo.all_reduce ...`
        # and attribute-carrying ones in generic form as
        # `"stablehlo.all_reduce"(...` -- accept both spellings, and
        # require a terminator so all_gather never counts all_to_all
        n = len(re.findall(re.escape(op) + r'["\s(]', text))
        if n:
            counts[name] = counts.get(name, 0) + n
    return counts


def collectives_from_compiled(text):
    counts = {}
    for op, name in _HLO_COLLECTIVES.items():
        n = len(re.findall(r" " + re.escape(op) + r"\(", text))
        if n:
            counts[name] = counts.get(name, 0) + n
    return counts


def fusions_from_compiled(text):
    return len(re.findall(r" fusion\(", text))


#: ``CompiledMemoryStats`` attributes -> portable summary keys
_MEMORY_FIELDS = (
    ("argument_size_in_bytes", "argument_bytes"),
    ("output_size_in_bytes", "output_bytes"),
    ("temp_size_in_bytes", "temp_bytes"),
    ("alias_size_in_bytes", "alias_bytes"),
    ("generated_code_size_in_bytes", "generated_code_bytes"),
)


def memory_analysis_summary(compiled_or_stats):
    """Normalize an executable's ``memory_analysis()`` into the portable
    ``{argument_bytes, output_bytes, temp_bytes, alias_bytes,
    generated_code_bytes, peak_bytes}`` dict, or None where the backend
    reports nothing (some CPU paths).  Accepts either the compiled
    object or the stats object itself, and tolerates dict-shaped stats
    -- THE one probe, shared by ``StepTelemetry.attach_cost``,
    ``tools/hlo_audit.py`` and ``tools/profile_resnet.py`` so all three
    report identical fields.

    ``peak_bytes`` is the budget estimate ``arguments + outputs + temps
    - aliased`` (aliased bytes are input buffers reused as outputs, so
    they are not paid twice)."""
    stats = compiled_or_stats
    if hasattr(stats, "memory_analysis"):
        try:
            stats = stats.memory_analysis()
        except Exception:
            return None
    if stats is None:
        return None
    if isinstance(stats, (list, tuple)):
        stats = stats[0] if stats else None
        if stats is None:
            return None
    out = {}
    for attr, key in _MEMORY_FIELDS:
        if isinstance(stats, dict):
            v = stats.get(attr, stats.get(key))
        else:
            v = getattr(stats, attr, None)
        if v is not None:
            try:
                out[key] = int(v)
            except (TypeError, ValueError):
                continue
    if not out:
        return None
    peak = (out.get("argument_bytes", 0) + out.get("output_bytes", 0)
            + out.get("temp_bytes", 0) - out.get("alias_bytes", 0))
    out["peak_bytes"] = max(int(peak), 0)
    return out


# --------------------------------------------------------------------- #
# summaries
# --------------------------------------------------------------------- #

def _float_dtype(dt):
    return bool(dt) and (dt.startswith("float") or dt.startswith("bfloat"))


def _donation_coverage(entries, donated_idx, min_bytes):
    """Fold the per-parameter donation bits into per-plane coverage.
    ``undonated`` lists only float leaves >= ``min_bytes`` -- the
    planes whose missing donation doubles peak HBM; scalar step
    counters and bool flags are noise, not leaks."""
    cov = {}
    for i, e in enumerate(entries):
        c = cov.setdefault(e["label"], {
            "leaves": 0, "bytes": 0, "donated_leaves": 0,
            "donated_bytes": 0, "undonated": []})
        c["leaves"] += 1
        b = e["bytes"] or 0
        c["bytes"] += b
        if i in donated_idx:
            c["donated_leaves"] += 1
            c["donated_bytes"] += b
        elif _float_dtype(e["dtype"]) and b >= min_bytes:
            c["undonated"].append({"path": e["path"], "bytes": b,
                                   "dtype": e["dtype"]})
    return cov


def lowering_summary(lowered, example_args, arg_labels=None,
                     min_bytes=2048):
    """Audit a ``jitted.lower(...)`` result without compiling (the
    cheap path ``StepTelemetry.attach_cost`` stamps on run headers)."""
    text = lowered.as_text()
    entries = arg_entries(example_args, arg_labels)
    summary = {
        "source": "lowering",
        "donation": _donation_coverage(
            entries, donated_params_from_lowering(text), min_bytes),
        "dot_conv_dtypes": dot_conv_from_lowering(text),
        "collectives": collectives_from_lowering(text),
    }
    return summary


def compiled_summary(compiled, example_args, arg_labels=None,
                     min_bytes=2048):
    """Audit an AOT-compiled step (``lowered.compile()``): the
    authoritative alias table plus post-optimization fusion and
    collective counts -- what ``tools/hlo_audit.py`` gates on."""
    text = compiled.as_text()
    entries = arg_entries(example_args, arg_labels)
    summary = {
        "source": "compiled",
        "donation": _donation_coverage(
            entries, aliased_params_from_compiled(text), min_bytes),
        "dot_conv_dtypes": dot_conv_from_compiled(text),
        "collectives": collectives_from_compiled(text),
        "fusions": fusions_from_compiled(text),
    }
    mem = memory_analysis_summary(compiled)
    if mem:
        summary["memory"] = mem
    return summary


def audit_step(jitted, *example_args, arg_labels=None, min_bytes=2048,
               compile=True):
    """Lower (and by default compile) a jitted step once and summarize
    it.  ``compile=False`` gives the lowering-only summary."""
    lowered = jitted.lower(*example_args)
    if not compile:
        return lowering_summary(lowered, example_args, arg_labels,
                                min_bytes)
    return compiled_summary(lowered.compile(), example_args, arg_labels,
                            min_bytes)


def format_summary_lines(summary, indent="  "):
    """Human-readable lines for one audit summary (donation coverage,
    dot/conv dtypes, collectives) -- THE one text rendering, shared by
    ``tools/obs_report.py`` and ``tools/hlo_audit.py`` so the two
    reports cannot drift."""
    out = []
    for label, cov in (summary.get("donation") or {}).items():
        line = (f"{indent}{label:<12} {cov['donated_leaves']}/"
                f"{cov['leaves']} leaves donated "
                f"({cov['donated_bytes']:,} / {cov['bytes']:,} bytes)")
        if cov.get("undonated"):
            line += "  UNDONATED: " + ", ".join(
                u["path"] for u in cov["undonated"][:4])
        out.append(line)
    for op, counts in (summary.get("dot_conv_dtypes") or {}).items():
        out.append(f"{indent}{op} dtypes: " + ", ".join(
            f"{dt} x{n}" for dt, n in sorted(counts.items())))
    if summary.get("collectives"):
        out.append(f"{indent}collectives: " + ", ".join(
            f"{k} x{v}" for k, v in sorted(summary["collectives"].items())))
    if "fusions" in summary:
        out.append(f"{indent}fusions: {summary['fusions']}")
    mem = summary.get("memory")
    if mem:
        parts = [f"{key.replace('_bytes', '')} {mem[key]:,}"
                 for key in ("argument_bytes", "output_bytes",
                             "temp_bytes", "generated_code_bytes")
                 if key in mem]
        line = f"{indent}memory budget: " + " + ".join(parts)
        if "peak_bytes" in mem:
            line += f"  (~{mem['peak_bytes']:,} bytes peak)"
        out.append(line)
    return out


def undonated_planes(summary, expected=("params", "opt_state")):
    """The gate predicate: ``[(label, [undonated leaf dicts])]`` for
    every expected-donated plane that has a large float leaf without an
    input/output alias (or donation marker).  Empty list = gate
    passes."""
    bad = []
    for label in expected:
        cov = summary["donation"].get(label)
        if cov is None:
            bad.append((label, [{"path": label, "bytes": None,
                                 "dtype": None,
                                 "error": "plane not in audit"}]))
        elif cov["undonated"]:
            bad.append((label, cov["undonated"]))
    return bad
