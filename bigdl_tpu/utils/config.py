"""Flag/config tier: ``BIGDL_*`` environment variables.

Reference: the ``-Dbigdl.*`` JVM system-property tier (SURVEY.md section 5
"Config / flag system": bigdl.engineType utils/Engine.scala:45,210;
bigdl.localMode / bigdl.coreNumber :158-187; bigdl.failure.retryTimes
optim/DistriOptimizer.scala:862-908; bigdl.Parameter.syncPoolSize
parameters/AllReduceParameter.scala:36).  JVM properties become env vars:
``-Dbigdl.failure.retryTimes=5`` -> ``BIGDL_FAILURE_RETRY_TIMES=5``.
"""

import os


def _get(name, default, cast):
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return cast(raw)
    except ValueError:
        raise ValueError(f"invalid {name}={raw!r}")


def engine_type():
    """Engine selector (reference: bigdl.engineType picks MklBlas/MklDnn;
    ConversionUtils.convert routes through the IR accordingly).  Values:
    'xla' (default -- direct modules ARE the xla engine), 'ir' (lift to
    IR and lower back through the xla mapping: exercises the engine
    seam), 'ir-quantized' (IR + int8 MXU engine)."""
    return os.environ.get("BIGDL_ENGINE_TYPE", "xla")


def local_mode():
    return _get("BIGDL_LOCAL_MODE", False, lambda s: s.lower() == "true")


def core_number():
    return _get("BIGDL_CORE_NUMBER", None, int)


def failure_retry_times():
    """Reference: bigdl.failure.retryTimes (default 5) — bound on the
    optimizer's restore-from-checkpoint retry loop."""
    return _get("BIGDL_FAILURE_RETRY_TIMES", 5, int)


def check_singleton():
    return _get("BIGDL_CHECK_SINGLETON", False, lambda s: s.lower() == "true")


def log_file():
    """Reference: LoggerFilter redirect path (bigdl.utils.LoggerFilter
    defaults to ./bigdl.log)."""
    return os.environ.get("BIGDL_LOG_FILE", None)


def redirect_spark_info_logs(path=None):
    """LoggerFilter.redirectSparkInfoLogs equivalent — delegating alias;
    the implementation lives in :mod:`bigdl_tpu.utils.logger_filter`."""
    from bigdl_tpu.utils.logger_filter import redirect_spark_info_logs
    return redirect_spark_info_logs(log_file=path or log_file())


def enable_compilation_cache(path=None):
    """Persistent XLA compilation cache: an earlier bench/evidence run in
    the same round warms the big compiles for later runs.  The env var is
    set BEFORE jax is imported so it applies even where
    ``jax.config.update`` rejects the option.

    ``path=None`` defaults to ``/tmp/jax_cache`` WITHOUT overriding an
    env var already in force; an explicit ``path`` (e.g. the
    ``--compilationCache`` CLI flag) wins over the env var.  Returns the
    active cache directory."""
    if path is None:
        os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")
    else:
        os.environ["JAX_COMPILATION_CACHE_DIR"] = path
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir",
                          os.environ["JAX_COMPILATION_CACHE_DIR"])
    except Exception:
        pass
    return os.environ["JAX_COMPILATION_CACHE_DIR"]


def compilation_cache_status():
    """``{"dir", "entries", "warm"}`` for the active compilation cache,
    or ``None`` when no cache dir is configured.  The ONE place the
    entry counting lives -- the log note below and the telemetry
    header both consume this, so they cannot disagree.  Sample it at
    run START: a lazily-taken count sees the run's own first compiles
    and misreports cold as warm."""
    d = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if not d:
        return None
    try:
        n = len(os.listdir(d)) if os.path.isdir(d) else 0
    except OSError:
        n = 0
    return {"dir": d, "entries": n, "warm": n > 0}


def compilation_cache_note():
    """One-line warm/cold note for logs and the telemetry header:
    whether the active compilation cache already holds compiled
    programs (repeat runs skip the big XLA compiles) or starts cold."""
    status = compilation_cache_status()
    if status is None:
        return "compilation cache: disabled"
    n = status["entries"]
    return (f"compilation cache at {status['dir']}: {n} cached programs "
            f"({'warm -- repeat compiles will hit' if n else 'cold'})")


def honor_env_platforms():
    """Re-assert the JAX_PLATFORMS env var's intent.

    The axon sitecustomize force-sets ``jax_platforms`` to the tunneled TPU
    at interpreter start, overriding the env var; every CLI/tool that wants
    CPU-forced runs must call this before touching jax.  (Shared helper --
    the same workaround used to be copy-pasted per entry point.)
    """
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        import jax

        try:
            jax.config.update("jax_platforms", want)
        except Exception:
            pass
