"""Checkpoint persistence.

Reference: utils/File.scala:27-130 (Java serialization + HDFS/S3),
optim/AbstractOptimizer.scala:206-226 (checkpoint of model.<neval> +
optimMethod.<neval>).

Format: a pickle of numpy-ified pytrees -- portable, no JVM.  (The
protobuf bigdl.proto-compatible model format is a separate interop layer;
see SURVEY.md section 2.6.)
"""

import os
import pickle
from typing import Any

import jax
import numpy as np


def _to_numpy(tree):
    return jax.tree.map(lambda x: np.asarray(x), tree)


def save(obj: Any, path: str):
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_numpy(obj), f)


def load(path: str) -> Any:
    with open(path, "rb") as f:
        return pickle.load(f)


def save_checkpoint(path: str, tag, model_params, model_state, opt_state,
                    driver_state):
    """One training snapshot (model + optimizer + loop counters), resumable."""
    save(
        {
            "model_params": model_params,
            "model_state": model_state,
            "opt_state": opt_state,
            "driver_state": dict(driver_state),
        },
        os.path.join(path, f"checkpoint.{tag}.pkl"),
    )


def latest_checkpoint(path: str):
    if not os.path.isdir(path):
        return None
    snaps = [f for f in os.listdir(path)
             if f.startswith("checkpoint.") and f.endswith(".pkl")]
    if not snaps:
        return None

    def tag(f):
        try:
            return int(f.split(".")[1])
        except ValueError:
            return -1

    return os.path.join(path, max(snaps, key=tag))
