"""Checkpoint persistence, local and remote.

Reference: utils/File.scala:27-130 -- saveToHdfs/load route any
``scheme://`` path through the Hadoop FileSystem API (HDFS/S3), plain
paths through java.io.  Here the same split: URL-schemed paths
(hdfs://, s3://, gs://, memory://, ...) go through fsspec when it is
installed; plain paths use the local fast path with no extra dependency.

Also: optim/AbstractOptimizer.scala:206-226 (checkpoint of model.<neval> +
optimMethod.<neval>).

Format: a pickle of numpy-ified pytrees -- portable, no JVM.  (The
protobuf bigdl.proto-compatible model format is a separate interop layer;
see SURVEY.md section 2.6.)
"""

import os
import pickle
import re
from typing import Any

import jax
import numpy as np

_SCHEME = re.compile(r"^[a-zA-Z][a-zA-Z0-9+.-]*://")


def _is_remote(path: str) -> bool:
    return bool(_SCHEME.match(str(path))) and not str(path).startswith(
        "file://")


def _fs_for(path: str):
    try:
        import fsspec
    except ImportError as e:          # pragma: no cover
        raise ImportError(
            f"reading/writing {path} needs the optional fsspec dependency "
            f"(reference parity: utils/File.scala HDFS/S3 support)") from e
    fs, _, paths = fsspec.get_fs_token_paths(path)
    return fs, paths[0]


def open_file(path: str, mode: str = "rb"):
    """Open a local path or any fsspec URL (hdfs://, s3://, gs://, ...)."""
    if _is_remote(path):
        fs, p = _fs_for(path)
        if "w" in mode:
            parent = p.rsplit("/", 1)[0]
            if parent:
                fs.makedirs(parent, exist_ok=True)
        return fs.open(p, mode)
    if "w" in mode:
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
    return open(path, mode)


def exists(path: str) -> bool:
    if _is_remote(path):
        fs, p = _fs_for(path)
        return fs.exists(p)
    return os.path.exists(path)


def listdir(path: str):
    if _is_remote(path):
        fs, p = _fs_for(path)
        if not fs.isdir(p):
            return []
        return [e.rsplit("/", 1)[-1] for e in fs.ls(p, detail=False)]
    if not os.path.isdir(path):
        return []
    return os.listdir(path)


def join(path: str, *parts: str) -> str:
    if _is_remote(path):
        return "/".join([str(path).rstrip("/")] + [p.strip("/")
                                                   for p in parts])
    return os.path.join(path, *parts)


def _to_numpy(tree):
    return jax.tree.map(lambda x: np.asarray(x), tree)


def save(obj: Any, path: str):
    with open_file(path, "wb") as f:
        pickle.dump(_to_numpy(obj), f)


def abs_local(path: str) -> str:
    """Absolute path for plain local paths (orbax requirement); remote
    URL-schemed paths (gs://, hdfs://) pass through untouched."""
    return path if "://" in str(path) else os.path.abspath(path)


def load(path: str) -> Any:
    with open_file(path, "rb") as f:
        return pickle.load(f)


def save_checkpoint(path: str, tag, model_params, model_state, opt_state,
                    driver_state):
    """One training snapshot (model + optimizer + loop counters), resumable."""
    save(
        {
            "model_params": model_params,
            "model_state": model_state,
            "opt_state": opt_state,
            "driver_state": dict(driver_state),
        },
        join(path, f"checkpoint.{tag}.pkl"),
    )


def latest_checkpoint(path: str):
    snaps = [f for f in listdir(path)
             if f.startswith("checkpoint.") and f.endswith(".pkl")]
    if not snaps:
        return None

    def tag(f):
        try:
            return int(f.split(".")[1])
        except ValueError:
            return -1

    return join(path, max(snaps, key=tag))
