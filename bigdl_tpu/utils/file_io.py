"""Checkpoint persistence, local and remote -- crash-safe by default.

Reference: utils/File.scala:27-130 -- saveToHdfs/load route any
``scheme://`` path through the Hadoop FileSystem API (HDFS/S3), plain
paths through java.io.  Here the same split: URL-schemed paths
(hdfs://, s3://, gs://, memory://, ...) go through fsspec when it is
installed; plain paths use the local fast path with no extra dependency.

Also: optim/AbstractOptimizer.scala:206-226 (checkpoint of model.<neval> +
optimMethod.<neval>).

Format: a pickle of numpy-ified pytrees -- portable, no JVM.  (The
protobuf bigdl.proto-compatible model format is a separate interop layer;
see SURVEY.md section 2.6.)

Crash safety (docs/robustness.md):

- every snapshot writes to a TEMP name and atomically renames into
  place, so a writer killed mid-write never shadows the previous good
  snapshot with a truncated file;
- each snapshot gets a sidecar MANIFEST (``<name>.manifest.json``)
  stamping byte count + sha256 of every file it covers, plus the
  ``layout`` block (``parallel/reshard.LayoutSpec``: strategy kind,
  mesh axes/degrees, per-plane partition spec) that makes every
  snapshot SELF-DESCRIBING -- what a resume on a different mesh or a
  layout-aware serving refresh redistributes from (docs/robustness.md,
  "Portable resharding");
- resume-time resolution (``scan_checkpoints`` / ``latest_checkpoint``)
  VERIFIES candidates newest-first and quarantines failures (renamed to
  ``*.corrupt``, evidence preserved) instead of crashing on -- or worse,
  silently loading -- garbage;
- checkpoint writes retry transient IO failures with bounded backoff
  (``with_write_retries``) instead of killing the training step that
  triggered the checkpoint callback.
"""

import hashlib
import json
import logging
import os
import pickle
import re
import shutil
import time
from typing import Any

import numpy as np

log = logging.getLogger("bigdl_tpu.optim")

_SCHEME = re.compile(r"^[a-zA-Z][a-zA-Z0-9+.-]*://")

#: sidecar integrity manifest next to every snapshot
MANIFEST_SUFFIX = ".manifest.json"
#: a snapshot that failed verification is renamed, never deleted
QUARANTINE_SUFFIX = ".corrupt"
#: in-flight writes carry this marker until the atomic rename
TMP_MARKER = ".tmp-"


def _is_remote(path: str) -> bool:
    return bool(_SCHEME.match(str(path))) and not str(path).startswith(
        "file://")


def is_remote(path: str) -> bool:
    """True for URL-schemed (fsspec-routed) paths -- callers branch on
    this to pick the local atomic-rename write path."""
    return _is_remote(path)


def _fs_for(path: str):
    try:
        import fsspec
    except ImportError as e:          # pragma: no cover
        raise ImportError(
            f"reading/writing {path} needs the optional fsspec dependency "
            f"(reference parity: utils/File.scala HDFS/S3 support)") from e
    fs, _, paths = fsspec.get_fs_token_paths(path)
    return fs, paths[0]


def open_file(path: str, mode: str = "rb"):
    """Open a local path or any fsspec URL (hdfs://, s3://, gs://, ...)."""
    if _is_remote(path):
        fs, p = _fs_for(path)
        if "w" in mode:
            parent = p.rsplit("/", 1)[0]
            if parent:
                fs.makedirs(parent, exist_ok=True)
        return fs.open(p, mode)
    if "w" in mode:
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
    return open(path, mode)


def exists(path: str) -> bool:
    if _is_remote(path):
        fs, p = _fs_for(path)
        return fs.exists(p)
    return os.path.exists(path)


def listdir(path: str):
    if _is_remote(path):
        fs, p = _fs_for(path)
        if not fs.isdir(p):
            return []
        return [e.rsplit("/", 1)[-1] for e in fs.ls(p, detail=False)]
    if not os.path.isdir(path):
        return []
    return os.listdir(path)


def join(path: str, *parts: str) -> str:
    if _is_remote(path):
        return "/".join([str(path).rstrip("/")] + [p.strip("/")
                                                   for p in parts])
    return os.path.join(path, *parts)


def getsize(path: str) -> int:
    if _is_remote(path):
        fs, p = _fs_for(path)
        return int(fs.size(p))
    return os.path.getsize(path)


def isdir(path: str) -> bool:
    if _is_remote(path):
        fs, p = _fs_for(path)
        return fs.isdir(p)
    return os.path.isdir(path)


def rename(src: str, dst: str):
    """Atomic replace for local paths; best-effort mv for remote ones
    (object stores have no true rename -- orbax's own commit marker is
    the atomicity story there)."""
    if _is_remote(src):
        fs, s = _fs_for(src)
        _, d = _fs_for(dst)
        fs.mv(s, d, recursive=True)
        return
    os.replace(src, dst)


def sha256_of(path: str) -> str:
    h = hashlib.sha256()
    with open_file(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _to_numpy(tree):
    # jax only here (lazily): everything else in this module is plain
    # IO, usable by supervisor/report processes that never touch a
    # backend (optim/recovery.py, tools/obs_report.py)
    import jax

    return jax.tree.map(lambda x: np.asarray(x), tree)


def save(obj: Any, path: str):
    with open_file(path, "wb") as f:
        pickle.dump(_to_numpy(obj), f)


def atomic_save(obj: Any, path: str):
    """``save`` through a temp name + rename: a writer killed mid-write
    leaves only a ``*.tmp-*`` orphan, never a truncated ``path``."""
    tmp = path + TMP_MARKER + str(os.getpid())
    with open_file(tmp, "wb") as f:
        pickle.dump(_to_numpy(obj), f)
        f.flush()
        try:
            os.fsync(f.fileno())
        except (OSError, AttributeError):  # remote/exotic filesystems
            pass
    rename(tmp, path)


def with_write_retries(fn, what="checkpoint write", retries=None,
                       backoff_s=0.1, sleep=time.sleep):
    """Run ``fn()`` retrying transient IO failures (``OSError``) with
    exponential backoff, one WARNING per retry; re-raise after the
    budget -- a flaky remote filesystem must not kill the training step
    that triggered the checkpoint callback (docs/robustness.md).
    Deterministic failures (pickling errors etc.) are not retried."""
    if retries is None:
        retries = int(os.environ.get("BIGDL_CKPT_WRITE_RETRIES", "2"))
    for attempt in range(retries + 1):
        try:
            return fn()
        except OSError as e:
            if attempt >= retries:
                raise
            delay = backoff_s * (2 ** attempt)
            log.warning("%s failed (%s); retry %d/%d in %.2fs",
                        what, e, attempt + 1, retries, delay)
            sleep(delay)


# --------------------------------------------------------------------------- #
# Snapshot integrity: sidecar manifests, verification, quarantine.
# --------------------------------------------------------------------------- #


def _walk_files(target: str):
    """Relative paths of every regular file under ``target`` (itself,
    when it is a file), keyed relative to its PARENT directory -- the
    manifest's key space, shared by files and orbax snapshot dirs."""
    base = os.path.basename(str(target).rstrip("/"))
    if not isdir(target):
        return [base]
    out = []
    for root, _, files in os.walk(target):
        rel_root = os.path.relpath(root, os.path.dirname(target))
        out.extend(os.path.join(rel_root, f) for f in files)
    return sorted(out)


def write_snapshot_manifest(target: str, extra_files=(), meta=None):
    """Stamp ``<target>.manifest.json``: bytes + sha256 of every file
    the snapshot consists of (a pickle file, or an orbax directory plus
    sidecars like ``snap_N.driver``), plus caller metadata (the dp
    layout block the N->M resume reads).  Written atomically, AFTER the
    snapshot itself renames into place: a manifest's presence implies
    the files it covers were fully written."""
    parent = os.path.dirname(str(target).rstrip("/"))
    rels = _walk_files(target) + [os.path.basename(str(f)) for f in
                                  extra_files]
    files = {}
    for rel in rels:
        p = join(parent, rel) if parent else rel
        if _is_remote(target) and isdir(target):
            continue  # remote dir digests: orbax's commit marker governs
        files[rel] = {"bytes": getsize(p), "sha256": sha256_of(p)}
    manifest = {"schema_version": 1, "created": time.time(),
                "kind": "dir" if isdir(target) else "file",
                "files": files}
    if meta:
        manifest.update(meta)
    mpath = str(target).rstrip("/") + MANIFEST_SUFFIX
    tmp = mpath + TMP_MARKER + str(os.getpid())
    with open_file(tmp, "wb") as f:
        f.write(json.dumps(manifest, indent=1).encode())
    rename(tmp, mpath)
    return mpath


def read_manifest(target: str):
    """The parsed sidecar manifest of a snapshot path, or None (absent
    or unparseable -- an unparseable manifest must not brick resume)."""
    mpath = str(target).rstrip("/") + MANIFEST_SUFFIX
    if not exists(mpath):
        return None
    try:
        with open_file(mpath, "rb") as f:
            return json.loads(f.read().decode(errors="replace"))
    except (OSError, ValueError):
        return None


def verify_snapshot(target: str, legacy_load: bool = False):
    """-> None when the snapshot passes integrity verification, else a
    human-readable reason.  With a manifest: every covered file must
    exist with the stamped size and sha256 (catches truncation AND
    bit-flips).  Without one (legacy snapshot, or a crash landed
    between the data rename and the manifest rename): ``legacy_load``
    falls back to an unpickle attempt for pickle snapshots; directories
    are accepted (orbax's own commit marker governs)."""
    if not exists(target):
        return "missing"
    manifest = read_manifest(target)
    if manifest is None:
        if legacy_load and not isdir(target):
            try:
                load(target)
            except Exception as e:
                return f"no manifest and unreadable pickle ({e!r:.120})"
        return None
    parent = os.path.dirname(str(target).rstrip("/"))
    for rel, rec in (manifest.get("files") or {}).items():
        p = join(parent, rel) if parent else rel
        if not exists(p):
            return f"{rel}: missing"
        size = getsize(p)
        if size != rec.get("bytes"):
            return f"{rel}: {size} bytes, manifest says {rec.get('bytes')}"
        if sha256_of(p) != rec.get("sha256"):
            return f"{rel}: sha256 mismatch"
    return None


def write_sharded_snapshot(d: str, save_dir, driver_state,
                           manifest_meta=None, direct=False,
                           write_manifest=True):
    """The ONE crash-safe commit protocol for directory (orbax)
    snapshots, shared by the Distri and Strategy savers
    (docs/robustness.md).  ``save_dir(path)`` writes the snapshot
    directory at ``path`` (the caller's orbax save closure).

    Local single-host (``direct=False``): save into a temp dir, write
    the ``.driver`` sidecar atomically, swap the temp dir into place,
    then stamp the manifest -- a kill at any point never shadows the
    previous snapshot with a partial one.  The swap REPLACES an
    existing target dir (a retry after a mid-commit transient, or a
    same-tag re-save): the stale dir is removed only once the fresh
    temp dir is fully written beside it, so the worst crash window
    leaves no dir at ``d`` (scan skips it, resume falls back).

    Remote / multi-host (``direct=True``): save straight to ``d`` --
    orbax's own commit marker governs atomicity there -- with the
    manifest written only when ``write_manifest`` (callers pass
    ``process_index() == 0``).

    The whole protocol retries transient IO failures
    (``with_write_retries``), and every step of it is retry-safe.
    """
    def write():
        if direct:
            save_dir(d)
            save(dict(driver_state), d + ".driver")
            if write_manifest:
                write_snapshot_manifest(
                    d, extra_files=(d + ".driver",), meta=manifest_meta)
            return
        tmp = d + TMP_MARKER + str(os.getpid())
        save_dir(tmp)
        atomic_save(dict(driver_state), d + ".driver")
        if os.path.isdir(d):
            # retrying past a successful swap, or overwriting the same
            # tag: the replacement is complete at `tmp`, so dropping
            # the stale dir first is safe (a crash in between leaves
            # NO dir at d -- skipped by scan, previous snapshot wins)
            shutil.rmtree(d)
        rename(tmp, d)
        write_snapshot_manifest(
            d, extra_files=(d + ".driver",), meta=manifest_meta)

    with_write_retries(write, what=f"sharded snapshot ({d})")
    return d


def quarantine_snapshot(target: str, sidecars=()):
    """Rename a failed snapshot (+ its manifest and sidecars) to
    ``*.corrupt`` -- out of resume's way, evidence preserved.  Returns
    the quarantined paths."""
    moved = []
    for p in [str(target).rstrip("/"),
              str(target).rstrip("/") + MANIFEST_SUFFIX] + \
            [str(s) for s in sidecars]:
        if not exists(p):
            continue
        try:
            rename(p, p + QUARANTINE_SUFFIX)
            moved.append(p + QUARANTINE_SUFFIX)
        except OSError:  # pragma: no cover - quarantine is best-effort
            log.warning("could not quarantine %s", p, exc_info=True)
    if moved:
        log.warning("quarantined corrupt snapshot: %s", moved)
    return moved


def abs_local(path: str) -> str:
    """Absolute path for plain local paths (orbax requirement); remote
    URL-schemed paths (gs://, hdfs://) pass through untouched."""
    return path if "://" in str(path) else os.path.abspath(path)


def load(path: str) -> Any:
    with open_file(path, "rb") as f:
        return pickle.load(f)


def save_checkpoint(path: str, tag, model_params, model_state, opt_state,
                    driver_state, manifest_meta=None):
    """One training snapshot (model + optimizer + loop counters),
    resumable.  Crash-safe: temp-write + atomic rename, sidecar digest
    manifest, transient-IO retries (docs/robustness.md)."""
    target = join(path, f"checkpoint.{tag}.pkl")
    payload = {
        "model_params": model_params,
        "model_state": model_state,
        "opt_state": opt_state,
        "driver_state": dict(driver_state),
    }

    def write():
        atomic_save(payload, target)
        write_snapshot_manifest(target, meta=manifest_meta)

    with_write_retries(write, what=f"checkpoint write ({target})")
    return target


def _ckpt_tag(name):
    try:
        return int(str(name).split(".")[1].split("_")[-1])
    except (ValueError, IndexError):
        return -1


def scan_checkpoints(path: str):
    """-> ([newest intact snapshot path] or [], quarantined paths).

    Verifies ``checkpoint.<tag>.pkl`` candidates NEWEST-FIRST (manifest
    digest, or an unpickle attempt for manifest-less legacy files),
    quarantining failures on the spot, and STOPS at the first intact
    one -- resolution costs O(newest snapshot), not O(every retained
    snapshot's bytes), no matter how many old snapshots the run keeps.
    Older candidates stay unverified until a later resolution actually
    reaches them (e.g. after the newest was quarantined)."""
    quarantined = []
    snaps = sorted((f for f in listdir(path)
                    if f.startswith("checkpoint.") and f.endswith(".pkl")),
                   key=_ckpt_tag, reverse=True)
    for name in snaps:
        target = join(path, name)
        reason = verify_snapshot(target, legacy_load=True)
        if reason is None:
            return [target], quarantined
        log.warning("snapshot %s failed verification (%s)",
                    target, reason)
        quarantined.extend(quarantine_snapshot(target))
    return [], quarantined


def latest_checkpoint(path: str):
    """Newest INTACT snapshot (corrupt ones are quarantined), or None."""
    intact, _ = scan_checkpoints(path)
    return intact[0] if intact else None


def scan_sharded_snapshots(path: str):
    """Sharded (orbax) analogue of ``scan_checkpoints``: -> ([newest
    intact ``snap_<n>`` dir] or [], quarantined paths), verifying
    newest-first and stopping at the first intact one (older dirs stay
    unverified until actually needed).  A usable snapshot needs its
    ``.driver`` sidecar (a crash between the orbax finalize and the
    sidecar write leaves it unusable -- skipped, like before) and must
    pass manifest verification when a manifest exists (legacy
    manifest-less dirs are accepted; orbax's commit marker governs
    their atomicity)."""
    quarantined = []
    snaps = sorted(
        (d for d in listdir(path)
         if d.startswith("snap_") and TMP_MARKER not in d
         and not d.endswith(QUARANTINE_SUFFIX)
         and d.split("_")[-1].isdigit()),
        key=lambda d: int(d.split("_")[-1]), reverse=True)
    for name in snaps:
        target = join(path, name)
        driver = target + ".driver"
        if not exists(driver):
            continue   # unusable leftover, not corruption evidence
        reason = verify_snapshot(target)
        if reason is None:
            return [target], quarantined
        log.warning("sharded snapshot %s failed verification (%s)",
                    target, reason)
        quarantined.extend(quarantine_snapshot(target,
                                               sidecars=(driver,)))
    return [], quarantined
