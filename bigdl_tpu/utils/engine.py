"""Runtime bring-up: device discovery and mesh construction.

The reference's ``Engine`` (utils/Engine.scala:105) parses the Spark conf to
learn node/core counts, selects an engine type (MklBlas vs MklDnn) and owns
the thread pools.  On TPU the runtime is the XLA client: ``Engine.init``
optionally calls ``jax.distributed.initialize`` for multi-host, discovers the
device grid, and builds the ``jax.sharding.Mesh`` that every distributed
component (DistriOptimizer, ZeRO-1 chunking, sequence parallelism) shards
over.  There are no thread pools to manage -- XLA owns device threading --
so the Engine is mostly mesh bookkeeping plus global config.
"""

import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


class Engine:
    """Singleton runtime configuration (reference: utils/Engine.scala)."""

    _initialized = False
    _mesh: Optional[Mesh] = None
    _node_number: int = 1
    _core_number: int = 1  # devices per host on TPU

    #: axis names used by the default data-parallel mesh
    DATA_AXIS = "data"
    MODEL_AXIS = "model"

    @classmethod
    def init(
        cls,
        coordinator_address: Optional[str] = None,
        num_processes: Optional[int] = None,
        process_id: Optional[int] = None,
        mesh_shape: Optional[Tuple[int, ...]] = None,
        axis_names: Sequence[str] = ("data",),
    ) -> "Engine":
        """Initialise the runtime.

        Single-host: just discovers local devices.  Multi-host: pass the
        coordinator address (the analogue of the reference's Spark-conf
        executor discovery, utils/Engine.scala:113-116) and JAX's distributed
        runtime handles rendezvous; collectives then ride ICI within a slice
        and DCN across slices automatically.
        """
        if coordinator_address is None:
            # launcher-script surface (reference: scripts/*-with-bigdl.sh
            # export SPARK_* conf): a k8s manifest or mpirun wrapper sets
            # these so every CLI entry point joins the rendezvous without
            # code changes (see docker/k8s-multihost.yaml)
            coordinator_address = os.environ.get("BIGDL_COORDINATOR")
            if coordinator_address is not None:
                if num_processes is None and "BIGDL_NUM_PROCESSES" in os.environ:
                    num_processes = int(os.environ["BIGDL_NUM_PROCESSES"])
                if process_id is None and "BIGDL_PROCESS_ID" in os.environ:
                    process_id = int(os.environ["BIGDL_PROCESS_ID"])
        if coordinator_address is not None and not cls._initialized:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
        cls._node_number = jax.process_count()
        cls._core_number = jax.local_device_count()
        cls._mesh = cls.build_mesh(mesh_shape, axis_names)
        cls._initialized = True
        return cls

    @classmethod
    def build_mesh(
        cls,
        mesh_shape: Optional[Tuple[int, ...]] = None,
        axis_names: Sequence[str] = ("data",),
    ) -> Mesh:
        """Build a Mesh over all devices.

        Default: a 1-D data-parallel mesh over every chip -- the analogue of
        the reference's one-model-replica-per-core layout.  Pass a
        ``mesh_shape`` like ``(2, 4)`` with ``axis_names=("data", "model")``
        for hybrid data+model parallelism.
        """
        devices = np.asarray(jax.devices())
        if mesh_shape is None:
            mesh_shape = (devices.size,)
        if int(np.prod(mesh_shape)) != devices.size:
            raise ValueError(
                f"mesh_shape {mesh_shape} does not cover {devices.size} devices"
            )
        return Mesh(devices.reshape(mesh_shape), axis_names=tuple(axis_names))

    @classmethod
    def mesh(cls) -> Mesh:
        if cls._mesh is None:
            cls._mesh = cls.build_mesh()
        return cls._mesh

    @classmethod
    def set_mesh(cls, mesh: Mesh):
        cls._mesh = mesh

    @classmethod
    def node_number(cls) -> int:
        return cls._node_number if cls._initialized else jax.process_count()

    @classmethod
    def core_number(cls) -> int:
        return cls._core_number if cls._initialized else jax.local_device_count()

    @classmethod
    def device_count(cls) -> int:
        return jax.device_count()

    @classmethod
    def reset(cls):
        cls._initialized = False
        cls._mesh = None
