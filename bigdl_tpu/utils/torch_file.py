"""Torch7 .t7 binary serialization (read/write).

Reference: utils/TorchFile.scala — little-endian stream of typed objects:
type ids TYPE_NIL=0 / NUMBER=1 (f64) / STRING=2 (i32 len + bytes) /
TABLE=3 (i32 memo index, i32 count, key/value objects) /
TORCH=4 (i32 memo index, version string "V 1", class name, payload) /
BOOLEAN=5 (i32).  Tensor payload: i32 ndim, i64[ndim] sizes, i64[ndim]
strides, i64 storageOffset (1-based), storage object; storage payload:
i64 length + raw elements (TorchFile.scala:710-719 readDoubleStorage,
:398-421 writeDoubleTensor).

Scope: numbers, booleans, strings, tables (<-> dict), numpy arrays
(<-> torch.FloatTensor / DoubleTensor / LongTensor).  nn.* module objects
are read into plain dicts with a ``__torch_class__`` key; writing module
objects is not supported (use the BigDL protobuf or caffe interop for
model exchange).
"""

import struct

import numpy as np

TYPE_NIL = 0
TYPE_NUMBER = 1
TYPE_STRING = 2
TYPE_TABLE = 3
TYPE_TORCH = 4
TYPE_BOOLEAN = 5
TYPE_FUNCTION = 6
LEGACY_TYPE_RECUR_FUNCTION = 7
TYPE_RECUR_FUNCTION = 8

_TENSOR_DTYPES = {
    "torch.FloatTensor": np.float32, "torch.DoubleTensor": np.float64,
    "torch.LongTensor": np.int64, "torch.IntTensor": np.int32,
    "torch.ByteTensor": np.uint8, "torch.CudaTensor": np.float32,
    "torch.CudaDoubleTensor": np.float64, "torch.CudaLongTensor": np.int64,
}
_STORAGE_DTYPES = {
    "torch.FloatStorage": np.float32, "torch.DoubleStorage": np.float64,
    "torch.LongStorage": np.int64, "torch.IntStorage": np.int32,
    "torch.ByteStorage": np.uint8, "torch.CudaStorage": np.float32,
    "torch.CudaDoubleStorage": np.float64,
    "torch.CudaLongStorage": np.int64,
}
_NP_TO_TENSOR = {
    np.dtype(np.float32): ("torch.FloatTensor", "torch.FloatStorage", "<f4"),
    np.dtype(np.float64): ("torch.DoubleTensor", "torch.DoubleStorage",
                           "<f8"),
    np.dtype(np.int64): ("torch.LongTensor", "torch.LongStorage", "<i8"),
    np.dtype(np.int32): ("torch.IntTensor", "torch.IntStorage", "<i4"),
}


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0
        self.memo = {}

    def _unpack(self, fmt, size):
        v = struct.unpack_from(fmt, self.data, self.pos)[0]
        self.pos += size
        return v

    def i32(self):
        return self._unpack("<i", 4)

    def i64(self):
        return self._unpack("<q", 8)

    def f64(self):
        return self._unpack("<d", 8)

    def string(self):
        n = self.i32()
        s = self.data[self.pos:self.pos + n].decode("latin-1")
        self.pos += n
        return s

    def raw(self, dtype, count):
        arr = np.frombuffer(self.data, dtype=dtype, count=count,
                            offset=self.pos).copy()
        self.pos += arr.itemsize * count
        return arr

    def obj(self):
        tid = self.i32()
        if tid == TYPE_NIL:
            return None
        if tid == TYPE_NUMBER:
            v = self.f64()
            return int(v) if v == int(v) and abs(v) < 2 ** 53 else v
        if tid == TYPE_STRING:
            return self.string()
        if tid == TYPE_BOOLEAN:
            return bool(self.i32())
        if tid == TYPE_TABLE:
            idx = self.i32()
            if idx in self.memo:
                return self.memo[idx]
            n = self.i32()
            out = {}
            self.memo[idx] = out
            for _ in range(n):
                k = self.obj()
                v = self.obj()
                out[k] = v
            return out
        if tid == TYPE_TORCH:
            idx = self.i32()
            if idx in self.memo:
                return self.memo[idx]
            version = self.string()
            cls = self.string() if version.startswith("V ") else version
            if cls in _TENSOR_DTYPES or cls in _STORAGE_DTYPES:
                result = self._torch_object(cls)
                self.memo[idx] = result
                return result
            # generic nn.* object: memo a placeholder BEFORE parsing the
            # payload so cyclic references (nngraph parents/children)
            # resolve instead of desyncing the stream
            holder = {"__torch_class__": cls}
            self.memo[idx] = holder
            payload = self.obj()
            if isinstance(payload, dict):
                holder.update(payload)
            else:
                holder["value"] = payload
            return holder
        raise NotImplementedError(f".t7 type id {tid}")

    def _torch_object(self, cls):
        if cls in _TENSOR_DTYPES:
            ndim = self.i32()
            sizes = [self.i64() for _ in range(ndim)]
            strides = [self.i64() for _ in range(ndim)]
            offset = self.i64()          # 1-based
            storage = self.obj()
            if storage is None:
                return np.zeros(sizes, _TENSOR_DTYPES[cls])
            flat = np.asarray(storage)
            return np.lib.stride_tricks.as_strided(
                flat[offset - 1:],
                shape=sizes,
                strides=[s * flat.itemsize for s in strides]).copy()
        if cls in _STORAGE_DTYPES:
            n = self.i64()
            return self.raw(np.dtype(_STORAGE_DTYPES[cls]).newbyteorder("<"),
                            n)
        # unknown torch class (e.g. nn.Linear): payload is a table
        payload = self.obj()
        if isinstance(payload, dict):
            payload["__torch_class__"] = cls
            return payload
        return {"__torch_class__": cls, "value": payload}


class _Writer:
    def __init__(self):
        self.chunks = []
        self.index = 0

    def i32(self, v):
        self.chunks.append(struct.pack("<i", int(v)))

    def i64(self, v):
        self.chunks.append(struct.pack("<q", int(v)))

    def f64(self, v):
        self.chunks.append(struct.pack("<d", float(v)))

    def string(self, s):
        b = s.encode("latin-1")
        self.i32(len(b))
        self.chunks.append(b)

    def obj(self, value):
        if value is None:
            self.i32(TYPE_NIL)
        elif isinstance(value, bool):
            self.i32(TYPE_BOOLEAN)
            self.i32(1 if value else 0)
        elif isinstance(value, (int, float, np.integer, np.floating)):
            self.i32(TYPE_NUMBER)
            self.f64(value)
        elif isinstance(value, str):
            self.i32(TYPE_STRING)
            self.string(value)
        elif isinstance(value, dict) and "__torch_class__" in value:
            # serialized torch object (e.g. an nn.* module table): emit
            # TYPE_TORCH with the class name and the rest as payload
            self.i32(TYPE_TORCH)
            self.index += 1
            self.i32(self.index)
            self.string("V 1")
            self.string(value["__torch_class__"])
            self.obj({k: v for k, v in value.items()
                      if k != "__torch_class__"})
        elif isinstance(value, dict):
            self.i32(TYPE_TABLE)
            self.index += 1
            self.i32(self.index)
            self.i32(len(value))
            for k, v in value.items():
                self.obj(k)
                self.obj(v)
        elif isinstance(value, (list, tuple)):
            # lua convention: 1-based integer-keyed table
            self.obj({i + 1: v for i, v in enumerate(value)})
        elif isinstance(value, np.ndarray):
            self._tensor(value)
        else:
            raise NotImplementedError(
                f".t7 write: unsupported type {type(value)}")

    def _tensor(self, arr):
        arr = np.ascontiguousarray(arr)
        if arr.dtype not in _NP_TO_TENSOR:
            arr = arr.astype(np.float32)
        tcls, scls, wire = _NP_TO_TENSOR[arr.dtype]
        self.i32(TYPE_TORCH)
        self.index += 1
        self.i32(self.index)
        self.string("V 1")
        self.string(tcls)
        self.i32(arr.ndim)
        strides, acc = [], 1
        for s in reversed(arr.shape):
            strides.append(acc)
            acc *= s
        strides = list(reversed(strides))
        for s in arr.shape:
            self.i64(s)
        for s in strides:
            self.i64(s)
        self.i64(1)                      # storageOffset, 1-based
        # storage object
        self.i32(TYPE_TORCH)
        self.index += 1
        self.i32(self.index)
        self.string("V 1")
        self.string(scls)
        self.i64(arr.size)
        self.chunks.append(arr.astype(wire).tobytes())


def load_t7(path):
    """Read a .t7 file -> python value (reference: TorchFile.load)."""
    with open(path, "rb") as f:
        return _Reader(f.read()).obj()


def save_t7(value, path, overwrite=True):
    """Write numbers/strings/bools/dicts/ndarrays as .t7
    (reference: TorchFile.save)."""
    import os
    if os.path.exists(path) and not overwrite:
        raise FileExistsError(path)
    w = _Writer()
    w.obj(value)
    with open(path, "wb") as f:
        f.write(b"".join(w.chunks))


# ---------------------------------------------------------------------------
# Torch nn module -> bigdl_tpu module (reference: Module.loadTorch /
# TorchFile.loadModule -- the loadmodel example path)
# ---------------------------------------------------------------------------

def _t7_modules_list(table):
    """The 'modules' entry of a container table -> ordered python list."""
    mods = table.get("modules", {})
    if isinstance(mods, dict):
        return [mods[k] for k in sorted(k for k in mods
                                        if isinstance(k, (int, float)))]
    return list(mods)


def load_torch_module(path, input_spec=None):
    """Read a .t7-serialized torch nn model into the equivalent module tree
    (reference: Module.loadTorch; weight layouts converted at the boundary:
    torch conv (out, in/g, kH, kW) -> HWIO, NCHW activations assumed, so
    containers get data_format adapters where needed).

    If ``input_spec`` is given the model is built immediately and BN
    running statistics are installed; otherwise weights install lazily on
    first build and running stats are pending the same way.
    """
    table = load_t7(path)
    mod = _torch_table_to_module(table)
    if input_spec is not None:
        mod.build(input_spec)
    return mod


_torch_reshape_cls = None


def _make_torch_reshape():
    """Reshape with torch (NCHW, channel-major) flatten semantics: 4-d
    activations are NHWC here, so transpose back to NCHW before the
    reshape -- the classic conv -> View -> Linear pattern then matches the
    verbatim-installed torch Linear weights.

    Built lazily so that plain .t7 tensor IO (load_t7/save_t7) never pays
    the jax + nn module-system import cost."""
    global _torch_reshape_cls
    if _torch_reshape_cls is not None:
        return _torch_reshape_cls
    import jax.numpy as jnp

    from bigdl_tpu.nn.module import Module

    class _TorchReshape(Module):
        def __init__(self, size):
            super().__init__()
            self.size = tuple(size)

        def apply(self, params, state, input, *, training=False, rng=None):
            x = input
            if x.ndim == 4:
                x = jnp.transpose(x, (0, 3, 1, 2))   # NHWC -> NCHW
            out = x.reshape((x.shape[0],) + self.size)
            if out.ndim == 4:
                raise NotImplementedError(
                    "torch Reshape/View to a 4-d spatial shape: the NCHW "
                    "result cannot feed NHWC convs without a per-model "
                    "layout adapter")
            return out, state

    _torch_reshape_cls = _TorchReshape
    return _TorchReshape


def _torch_table_to_module(t):
    import bigdl_tpu.nn as nn

    if not isinstance(t, dict) or "__torch_class__" not in t:
        raise ValueError(f"not a serialized torch module: {type(t)}")
    cls = t["__torch_class__"].split(".")[-1]

    if cls in ("Sequential",):
        seq = nn.Sequential()
        for sub in _t7_modules_list(t):
            seq.add(_torch_table_to_module(sub))
        return seq
    if cls == "ConcatTable":
        ct = nn.ConcatTable()
        for sub in _t7_modules_list(t):
            ct.add(_torch_table_to_module(sub))
        return ct
    if cls == "ParallelTable":
        pt = nn.ParallelTable()
        for sub in _t7_modules_list(t):
            pt.add(_torch_table_to_module(sub))
        return pt
    if cls == "Concat":
        # torch dimension is 1-based over NCHW (1=N, 2=C, 3=H, 4=W);
        # activations here are NHWC, so C -> -1, H -> 1, W -> 2
        tdim = int(t.get("dimension", 2))
        c = nn.Concat({1: 0, 2: -1, 3: 1, 4: 2}.get(tdim, tdim - 1))
        for sub in _t7_modules_list(t):
            c.add(_torch_table_to_module(sub))
        return c
    if cls == "CAddTable":
        return nn.CAddTable()
    if cls == "Identity":
        return nn.Identity()

    if cls == "Linear":
        w = np.asarray(t["weight"], np.float32)        # (out, in)
        m = nn.Linear(w.shape[1], w.shape[0],
                      with_bias="bias" in t and t["bias"] is not None)
        weights = [w] + ([np.asarray(t["bias"], np.float32)]
                         if m.with_bias else [])
        m.set_weights(weights)
        return m

    if cls == "SpatialConvolution":
        w = np.asarray(t["weight"], np.float32)
        groups = int(t.get("nGroup", 1))
        if w.ndim == 5:                                # grouped (g,out/g,in/g,kH,kW)
            w = w.reshape(-1, w.shape[2], w.shape[3], w.shape[4])
        n_out, cin_g, kh, kw = w.shape
        m = nn.SpatialConvolution(
            int(t["nInputPlane"]), int(t["nOutputPlane"]),
            int(t["kW"]), int(t["kH"]), int(t.get("dW", 1)),
            int(t.get("dH", 1)), int(t.get("padW", 0)), int(t.get("padH", 0)),
            n_group=groups,
            with_bias="bias" in t and t["bias"] is not None)
        hwio = w.transpose(2, 3, 1, 0)                 # -> (kH,kW,cin_g,out)
        weights = [hwio] + ([np.asarray(t["bias"], np.float32)]
                            if m.with_bias else [])
        m.set_weights(weights)
        return m

    if cls == "SpatialMaxPooling":
        m = nn.SpatialMaxPooling(
            int(t["kW"]), int(t["kH"]), int(t.get("dW", 1)),
            int(t.get("dH", 1)), int(t.get("padW", 0)), int(t.get("padH", 0)))
        if t.get("ceil_mode"):
            m.ceil()
        return m
    if cls == "SpatialAveragePooling":
        return nn.SpatialAveragePooling(
            int(t["kW"]), int(t["kH"]), int(t.get("dW", 1)),
            int(t.get("dH", 1)), int(t.get("padW", 0)), int(t.get("padH", 0)))

    if cls in ("SpatialBatchNormalization", "BatchNormalization"):
        n = int(np.asarray(t["running_mean"]).shape[0])
        affine = "weight" in t and t["weight"] is not None
        make = (nn.SpatialBatchNormalization
                if cls == "SpatialBatchNormalization" else
                nn.BatchNormalization)
        m = make(n, eps=float(t.get("eps", 1e-5)),
                 momentum=float(t.get("momentum", 0.1)), affine=affine)
        if affine:
            m.set_weights([np.asarray(t["weight"], np.float32),
                           np.asarray(t["bias"], np.float32)])
        m.set_state_entries({
            "running_mean": np.asarray(t["running_mean"], np.float32),
            "running_var": np.asarray(t["running_var"], np.float32)})
        return m

    simple = {
        "ReLU": nn.ReLU, "Tanh": nn.Tanh, "Sigmoid": nn.Sigmoid,
        "LogSoftMax": nn.LogSoftMax, "SoftMax": nn.SoftMax,
        "ELU": nn.ELU, "SoftPlus": nn.SoftPlus, "Abs": nn.Abs,
    }
    if cls in simple:
        return simple[cls]()
    if cls == "Dropout":
        return nn.Dropout(float(t.get("p", 0.5)))
    if cls in ("Reshape", "View"):
        size = tuple(int(v) for v in np.asarray(t["size"]).astype(int).ravel())
        return _make_torch_reshape()(size)

    raise NotImplementedError(
        f"torch class {t['__torch_class__']} has no converter "
        f"(reference parity: TorchFile.scala loadModule table)")
