"""Model persistence.

Reference: utils/serializer/ (protobuf bigdl.proto model format with storage
dedup + big-model separate weight file), utils/File.scala (legacy Java
serialization).

PRIMARY format (round 2+): the language-neutral protobuf wire format
(interop/bigdl_format.py) -- wire-compatible moduleTypes for the reference
overlap set, generic reflection encoding (recorded constructor args +
flattened param/state leaves) for everything else.  Survives class
refactors between versions, unlike pickle.

``load_module`` still reads round-1 pickle files (sniffed by the pickle
magic byte).  ``save_weights``/``load_weights`` give an npz flat-tensor
format for interop.
"""

import os
import pickle

import jax
import numpy as np


def save_module(module, path: str, weight_path=None):
    """Persist architecture + weights + state (reference:
    ModulePersister.saveToFile, utils/serializer/ModuleLoader.scala:219)."""
    from bigdl_tpu.interop.bigdl_format import save_bigdl

    save_bigdl(module, path, weight_path=weight_path)


def load_module(path: str, input_spec=None, weight_path=None):
    """-> module with params/state restored (reference:
    ModuleLoader.loadFromFile).  Reads the protobuf format; round-1 pickle
    files are detected by the pickle magic and still load."""
    with open(path, "rb") as f:
        head = f.read(2)
    if head[:1] == b"\x80":      # pickle protocol >= 2 (round-1 format)
        with open(path, "rb") as f:
            payload = pickle.load(f)
        assert payload.get("format") == "bigdl_tpu.module.v1", \
            "unknown format"
        module = payload["module"]
        module._params = payload["params"]
        module._state = payload["state"]
        return module
    from bigdl_tpu.interop.bigdl_format import load_bigdl

    return load_bigdl(path, input_spec=input_spec, weight_path=weight_path)


def save_weights(module, path: str):
    """Flat npz of weights keyed by tree path (interop-friendly)."""
    from jax.tree_util import keystr, tree_flatten_with_path

    leaves, _ = tree_flatten_with_path(module._params)
    arrays = {keystr(p): np.asarray(l) for p, l in leaves}
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    np.savez(path, **arrays)


def load_weights(module, path: str):
    """Load npz weights into a built module (shapes must match)."""
    from jax.tree_util import keystr, tree_flatten_with_path, tree_unflatten

    arrays = np.load(path)
    leaves, treedef = tree_flatten_with_path(module._params)
    new = []
    for p, old in leaves:
        arr = arrays[keystr(p)]
        assert arr.shape == old.shape, (keystr(p), arr.shape, old.shape)
        new.append(arr.astype(old.dtype))
    module._params = tree_unflatten(treedef, new)
    return module
