"""Model persistence.

Reference: utils/serializer/ (protobuf bigdl.proto model format with storage
dedup + big-model separate weight file), utils/File.scala (legacy Java
serialization).

Round-1 format: a single pickle containing (a) the module object graph --
plain Python objects, no compiled state -- and (b) params/state pytrees as
numpy.  ``save_weights``/``load_weights`` additionally give an npz flat-
tensor format for interop.  (A bigdl.proto-compatible exporter is a later
interop layer; see SURVEY.md section 2.6.)
"""

import os
import pickle

import jax
import numpy as np


def _numpyify(tree):
    return jax.tree.map(np.asarray, tree)


def save_module(module, path: str):
    """Persist architecture + weights + state (reference:
    ModulePersister.saveToFile, utils/serializer/ModuleLoader.scala:219)."""
    params, state = module._params, module._state
    payload = {
        "format": "bigdl_tpu.module.v1",
        "module": module,          # architecture (python object graph)
        "params": _numpyify(params),
        "state": _numpyify(state),
    }
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    # strip live arrays off the module object before pickling
    saved = module._params, module._state, module._grads
    module._params = module._state = module._grads = None
    try:
        with open(path, "wb") as f:
            pickle.dump(payload, f)
    finally:
        module._params, module._state, module._grads = saved


def load_module(path: str):
    """-> module with params/state restored (reference: ModuleLoader.loadFromFile)."""
    with open(path, "rb") as f:
        payload = pickle.load(f)
    assert payload.get("format") == "bigdl_tpu.module.v1", "unknown format"
    module = payload["module"]
    module._params = payload["params"]
    module._state = payload["state"]
    return module


def save_weights(module, path: str):
    """Flat npz of weights keyed by tree path (interop-friendly)."""
    from jax.tree_util import keystr, tree_flatten_with_path

    leaves, _ = tree_flatten_with_path(module._params)
    arrays = {keystr(p): np.asarray(l) for p, l in leaves}
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    np.savez(path, **arrays)


def load_weights(module, path: str):
    """Load npz weights into a built module (shapes must match)."""
    from jax.tree_util import keystr, tree_flatten_with_path, tree_unflatten

    arrays = np.load(path)
    leaves, treedef = tree_flatten_with_path(module._params)
    new = []
    for p, old in leaves:
        arr = arrays[keystr(p)]
        assert arr.shape == old.shape, (keystr(p), arr.shape, old.shape)
        new.append(arr.astype(old.dtype))
    module._params = tree_unflatten(treedef, new)
    return module
