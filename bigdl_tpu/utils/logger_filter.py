"""LoggerFilter analogue: route log noise to a file, keep progress visible.

Reference: utils/LoggerFilter.scala — redirects the Spark/breeze/akka
log4j output AND the framework's own INFO records to ``bigdl.log``
(flags ``bigdl.utils.LoggerFilter.disable`` / ``.logFile`` /
``.enableSparkLog``), so the console keeps only the training progress.
The TPU stack's noisy third parties are jax's and the XLA/absl bridge's
loggers; the flags map to ``BIGDL_*`` env vars per the config tier.

This is the single implementation; ``utils.config.redirect_spark_info_logs``
is a delegating alias kept for its original call sites.
"""

import logging
import os

#: loggers whose output is redirected away from the console (the jax/XLA
#: analogue of the reference's org.apache.spark / breeze / akka list)
NOISY_LOGGERS = ("jax", "jax._src", "absl", "orbax", "etils")

_PATTERN = "%(asctime)s %(levelname)-5s %(name)s:%(lineno)d - %(message)s"
_installed = []


def redirect_spark_info_logs(log_file=None, level=logging.INFO):
    """``LoggerFilter.redirectSparkInfoLogs`` analogue.

    Noisy third-party loggers get a file handler and stop propagating to
    the console; the framework's own ``bigdl_tpu`` logger gets the same
    file handler WITHOUT losing its console output (the reference logs
    training progress to both).  Flags (reference table,
    LoggerFilter.scala:24-28):

    - ``BIGDL_LOGGER_FILTER_DISABLE=1`` — no-op.
    - ``BIGDL_LOGGER_FILTER_LOGFILE`` (or the config tier's
      ``BIGDL_LOG_FILE``) — target file (default ``<cwd>/bigdl.log``).
    - ``BIGDL_LOGGER_FILTER_ENABLE_SPARK_LOG=0`` — silence the noisy
      loggers entirely instead of redirecting them to the file.
    """
    if os.environ.get("BIGDL_LOGGER_FILTER_DISABLE", "").lower() \
            in ("1", "true"):
        return None
    log_file = (log_file
                or os.environ.get("BIGDL_LOGGER_FILTER_LOGFILE")
                or os.environ.get("BIGDL_LOG_FILE")
                or os.path.join(os.getcwd(), "bigdl.log"))
    to_file = os.environ.get("BIGDL_LOGGER_FILTER_ENABLE_SPARK_LOG",
                             "1").lower() not in ("0", "false")
    handler = (logging.FileHandler(log_file) if to_file
               else logging.NullHandler())
    if to_file:
        handler.setLevel(level)
        handler.setFormatter(logging.Formatter(_PATTERN))
    for name in NOISY_LOGGERS:
        logger = logging.getLogger(name)
        # enable the redirected level on the logger itself (the reference
        # appender threshold is INFO; an unset logger would filter INFO
        # out before any handler sees it)
        _installed.append((logger, handler, logger.level,
                           logger.propagate))
        logger.addHandler(handler)
        logger.propagate = False
        logger.setLevel(level)
    own = logging.getLogger("bigdl_tpu")
    _installed.append((own, handler, own.level, own.propagate))
    own.addHandler(handler)           # file copy; console output kept
    own.setLevel(level)
    return log_file


def restore():
    """Undo :func:`redirect_spark_info_logs` (mostly for tests)."""
    handlers = set()
    for logger, handler, prev_level, prev_propagate in _installed:
        logger.removeHandler(handler)
        logger.propagate = prev_propagate
        logger.setLevel(prev_level)
        handlers.add(handler)
    for handler in handlers:
        handler.close()
    _installed.clear()
