"""Finite-difference gradient checking.

Reference: test/.../GradientChecker.scala — perturbs each input/weight
entry and compares (f(x+e) - f(x-e)) / 2e with the analytic backward.
Here the analytic side is jax.grad of the module's pure apply, so the
checker validates both the layer's forward math and its differentiability.

Per-layer flattening/labelling and norm math are shared with the health
telemetry (``observability/health.py``): ``layer_grad_norms`` returns
exactly the numbers a ``HealthMonitor`` samples on-device, so "layer
['2']['weight'] has grad norm X" means the same thing in a gradient
check and in a run's ``health`` events.
"""

import numpy as np

import jax
import jax.numpy as jnp

from bigdl_tpu.observability.health import (flatten_with_labels,
                                            per_layer_grad_norms)


class GradientChecker:
    def __init__(self, perturbation=1e-3, precision=1e-2):
        self.perturbation = perturbation
        self.precision = precision

    def check_layer(self, module, input, sample=20, seed=0):
        """True iff numeric and analytic input-gradients agree.

        ``sample``: number of randomly chosen input coordinates to perturb
        (the reference checks all entries; sampling keeps CPU time sane for
        big tensors).
        """
        if not module.is_built():
            from bigdl_tpu.utils.shape import spec_of
            module.build(spec_of(input))
        params, state = module._params, module._state

        def scalar_loss(x):
            y, _ = module.apply(params, state, x, training=False, rng=None)
            leaves = jax.tree.leaves(y)
            return sum(jnp.sum(l) for l in leaves)

        analytic = np.asarray(jax.grad(scalar_loss)(input))
        x0 = np.asarray(input, np.float64)
        rng = np.random.default_rng(seed)
        flat_idx = rng.choice(x0.size, size=min(sample, x0.size),
                              replace=False)
        eps = self.perturbation
        max_err = 0.0
        for i in flat_idx:
            xp = x0.copy().ravel()
            xm = x0.copy().ravel()
            xp[i] += eps
            xm[i] -= eps
            fp = float(scalar_loss(jnp.asarray(
                xp.reshape(x0.shape), input.dtype)))
            fm = float(scalar_loss(jnp.asarray(
                xm.reshape(x0.shape), input.dtype)))
            numeric = (fp - fm) / (2 * eps)
            denom = max(abs(numeric), abs(analytic.ravel()[i]), 1.0)
            max_err = max(max_err, abs(numeric - analytic.ravel()[i]) / denom)
        return max_err < self.precision

    @staticmethod
    def _analytic_weight_grads(module, input):
        """-> (params, scalar_loss, jax.grad tree): the shared prelude
        of check_weight and layer_grad_norms, so the gradient check and
        the health-norm helper cannot silently diverge."""
        if not module.is_built():
            from bigdl_tpu.utils.shape import spec_of
            module.build(spec_of(input))
        params, state = module._params, module._state

        def scalar_loss(p):
            y, _ = module.apply(p, state, input, training=False, rng=None)
            return sum(jnp.sum(l) for l in jax.tree.leaves(y))

        return params, scalar_loss, jax.grad(scalar_loss)(params)

    def check_weight(self, module, input, sample=20, seed=0):
        """True iff numeric and analytic weight-gradients agree."""
        params, scalar_loss, analytic = self._analytic_weight_grads(
            module, input)
        _, leaves, treedef = flatten_with_labels(params)
        an_leaves = jax.tree.leaves(analytic)
        rng = np.random.default_rng(seed)
        eps = self.perturbation
        max_err = 0.0
        for li, leaf in enumerate(leaves):
            a = np.asarray(leaf, np.float64)
            g = np.asarray(an_leaves[li]).ravel()
            for i in rng.choice(a.size, size=min(sample, a.size),
                                replace=False):
                for sign, store in ((+1, "fp"), (-1, "fm")):
                    pert = a.copy().ravel()
                    pert[i] += sign * eps
                    new_leaves = list(leaves)
                    new_leaves[li] = jnp.asarray(pert.reshape(a.shape),
                                                 leaf.dtype)
                    val = float(scalar_loss(
                        jax.tree.unflatten(treedef, new_leaves)))
                    if sign > 0:
                        fp = val
                    else:
                        fm = val
                numeric = (fp - fm) / (2 * eps)
                denom = max(abs(numeric), abs(g[i]), 1.0)
                max_err = max(max_err, abs(numeric - g[i]) / denom)
        return max_err < self.precision

    def layer_grad_norms(self, module, input):
        """{layer label: analytic weight-gradient L2 norm} via the SAME
        per-layer helper the on-device health telemetry uses
        (``observability.health.per_layer_grad_norms``), so a gradient
        check and a run's ``health`` events name and measure layers
        identically."""
        _, _, analytic = self._analytic_weight_grads(module, input)
        labels = flatten_with_labels(analytic)[0]
        norms = np.asarray(per_layer_grad_norms(analytic))
        return dict(zip(labels, norms.tolist()))
