"""Keras-1.2.2-style API over bigdl_tpu (reference: nn/keras/*.scala,
Topology.scala:55,89,127).

Layers infer their underlying module from the input shape at build time --
the TPU-native analogue of the reference's KerasLayer.doBuild(inputShape)
"labor" pattern: our Module.setup already receives the input spec, so a
Keras layer is just a Module that constructs and delegates to nn modules
inside setup/apply.  Shape inference is jax.eval_shape (free, no tracing
cost at runtime).

    from bigdl_tpu.keras import Sequential, Dense
    model = Sequential()
    model.add(Dense(64, activation="relu", input_shape=(784,)))
    model.add(Dense(10, activation="softmax"))
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy")
    model.fit(x, y, batch_size=32, nb_epoch=2)
"""

from bigdl_tpu.keras.layers import (  # noqa: F401
    Activation, AtrousConvolution1D, AtrousConvolution2D, AveragePooling1D,
    AveragePooling2D, AveragePooling3D, BatchNormalization, Bidirectional,
    Convolution1D, Convolution2D, Convolution3D, Cropping1D, Cropping2D,
    Cropping3D, Deconvolution2D, Dense, Dropout, ELU, Embedding, Flatten,
    GRU, GaussianDropout, GaussianNoise, GlobalAveragePooling1D,
    GlobalAveragePooling2D, GlobalAveragePooling3D, GlobalMaxPooling1D,
    GlobalMaxPooling2D, GlobalMaxPooling3D, Highway, InputLayer, KerasLayer,
    LSTM, LeakyReLU, LocallyConnected1D, LocallyConnected2D, Masking,
    MaxPooling1D, MaxPooling2D, MaxPooling3D, MaxoutDense, Merge, PReLU,
    Permute, ReLUVariant, RepeatVector, Reshape, SReLU,
    SeparableConvolution2D,
    SimpleRNN, SoftMax, SpatialDropout1D, SpatialDropout2D,
    SpatialDropout3D, ThresholdedReLU, TimeDistributed, UpSampling1D,
    UpSampling2D, UpSampling3D, ZeroPadding1D, ZeroPadding2D, ZeroPadding3D,
)
from bigdl_tpu.keras.topology import Input, Model, Sequential  # noqa: F401
from bigdl_tpu.keras.converter import (  # noqa: F401
    load_keras, model_from_json, load_weights_hdf5,
)

# Keras-2/3 aliases (the importer normalises to the 1.2.2 names)
Conv1D = Convolution1D
Conv2D = Convolution2D
Conv3D = Convolution3D
Conv2DTranspose = Deconvolution2D
SeparableConv2D = SeparableConvolution2D
