"""Keras-1.2.2 layer set (reference: nn/keras/*.scala, 71 files).

Every layer is a ``Module`` whose ``setup`` builds the underlying
bigdl_tpu.nn "labor" from the inferred input spec -- the TPU-native
equivalent of the reference's ``KerasLayer.doBuild(inputShape)`` pattern
(nn/keras/KerasLayer.scala:165,233).  ``input_shape`` (sans batch) is only
needed on the first layer of a Sequential, exactly as in Keras.

dim_ordering: "th" (channels-first, the reference default) or "tf"
(channels-last).  Internally everything computes NHWC -- the natural TPU
layout -- with boundary transposes for "th" that XLA cancels between
consecutive layers.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.module import Module, child_rng
from bigdl_tpu.utils.shape import spec_of

# ------------------------------------------------------------------ #
# helpers
# ------------------------------------------------------------------ #

_ACTIVATIONS = {
    "tanh": nn.Tanh, "relu": nn.ReLU, "sigmoid": nn.Sigmoid,
    "softmax": nn.SoftMax, "softplus": nn.SoftPlus,
    "softsign": nn.SoftSign, "hard_sigmoid": nn.HardSigmoid,
    "linear": nn.Identity, "elu": nn.ELU, "gelu": nn.GELU,
    "silu": nn.SiLU, "log_softmax": nn.LogSoftMax,
}


def get_activation(name):
    if name is None or isinstance(name, Module):
        return name
    try:
        return _ACTIVATIONS[name]()
    except KeyError:
        raise ValueError(f"unknown activation {name!r}") from None


_INITS = {
    "glorot_uniform": "Xavier", "glorot_normal": "Xavier",
    "uniform": "RandomUniform", "normal": "RandomNormal",
    "he_normal": "MsraFiller", "he_uniform": "MsraFiller",
    "zero": "Zeros", "one": "Ones",
}


def _to_tuple(v, n=2):
    if isinstance(v, (tuple, list)):
        return tuple(v)
    return (v,) * n


class KerasLayer(Module):
    """Base: holds ``input_shape`` and an inferred labor module
    (reference: nn/keras/KerasLayer.scala:165)."""

    def __init__(self, input_shape=None, name=None):
        super().__init__(name)
        self.input_shape = tuple(input_shape) if input_shape else None
        self._labor = None
        self.activation = None

    # override ONE of (_build_labor, _call)
    def _build_labor(self, input_spec):
        return None

    def _call(self, params, state, x, training, rng):
        raise NotImplementedError(type(self).__name__)

    def setup(self, rng, input_spec):
        self._labor = self._build_labor(input_spec)
        if self._labor is None:
            return (), ()
        return self._labor.setup(rng, input_spec)

    def apply(self, params, state, input, *, training=False, rng=None):
        if self._labor is not None:
            y, state = self._labor.apply(params, state, input,
                                         training=training, rng=rng)
        else:
            y, state = self._call(params, state, input, training, rng)
        if self.activation is not None:
            y, _ = self.activation.apply((), (), y, training=training)
        return y, state

    def children(self):
        return [self._labor] if self._labor is not None else []


class _Spatial(KerasLayer):
    """Shared th/tf plumbing for layers over 3-D..5-D feature maps."""

    def __init__(self, dim_ordering="th", input_shape=None, name=None):
        super().__init__(input_shape, name)
        if dim_ordering not in ("th", "tf"):
            raise ValueError(f"dim_ordering must be th/tf: {dim_ordering}")
        self.dim_ordering = dim_ordering

    def _nlast(self, x):
        """channels-first -> channels-last"""
        if self.dim_ordering == "tf":
            return x
        nd = x.ndim if hasattr(x, "ndim") else len(x.shape)
        perm = (0,) + tuple(range(2, nd)) + (1,)
        return jnp.transpose(x, perm)

    def _nfirst(self, x):
        if self.dim_ordering == "tf":
            return x
        nd = x.ndim if hasattr(x, "ndim") else len(x.shape)
        perm = (0, nd - 1) + tuple(range(1, nd - 1))
        return jnp.transpose(x, perm)

    def _spec_nlast(self, spec):
        if self.dim_ordering == "tf":
            return spec
        nd = len(spec.shape)
        perm = (0,) + tuple(range(2, nd)) + (1,)
        return jax.ShapeDtypeStruct(
            tuple(spec.shape[p] for p in perm), spec.dtype)

    def setup(self, rng, input_spec):
        self._labor = self._build_labor(self._spec_nlast(input_spec))
        if self._labor is None:
            return (), ()
        return self._labor.setup(rng, self._spec_nlast(input_spec))

    def apply(self, params, state, input, *, training=False, rng=None):
        x = self._nlast(input)
        if self._labor is not None:
            y, state = self._labor.apply(params, state, x,
                                         training=training, rng=rng)
        else:
            y, state = self._call(params, state, x, training, rng)
        y = self._nfirst(y)
        if self.activation is not None:
            y, _ = self.activation.apply((), (), y, training=training)
        return y, state


# ------------------------------------------------------------------ #
# core
# ------------------------------------------------------------------ #


class InputLayer(KerasLayer):
    """Placeholder (reference: nn/keras/Input.scala)."""

    def _call(self, params, state, x, training, rng):
        return x, state


class Dense(KerasLayer):
    """reference: nn/keras/Dense.scala:49 -- nD input works on the last
    dim (labor = InferReshape+Linear+InferReshape for ndim > 2)."""

    def __init__(self, output_dim, init="glorot_uniform", activation=None,
                 bias=True, input_shape=None, name=None, **_):
        super().__init__(input_shape, name)
        self.output_dim = output_dim
        self.bias = bias
        self.init = init
        self.activation = get_activation(activation)

    def _build_labor(self, spec):
        in_dim = spec.shape[-1]
        lin = nn.Linear(in_dim, self.output_dim, with_bias=self.bias)
        if len(spec.shape) > 2:
            return (nn.Sequential()
                    .add(nn.InferReshape((-1, in_dim)))
                    .add(lin)
                    .add(nn.InferReshape((-1,) + tuple(spec.shape[1:-1])
                                         + (self.output_dim,))))
        return lin


class Activation(KerasLayer):
    """reference: nn/keras/Activation.scala"""

    def __init__(self, activation, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.activation = get_activation(activation)

    def _call(self, params, state, x, training, rng):
        return x, state


class Dropout(KerasLayer):
    def __init__(self, p, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.p = p

    def _build_labor(self, spec):
        return nn.Dropout(self.p)


class Flatten(KerasLayer):
    def _build_labor(self, spec):
        return nn.Flatten()


class Reshape(KerasLayer):
    """reference: nn/keras/Reshape.scala (supports one -1)."""

    def __init__(self, target_shape, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.target_shape = tuple(target_shape)

    def _build_labor(self, spec):
        return nn.InferReshape((-1,) + self.target_shape) \
            if -1 in self.target_shape else nn.Reshape(self.target_shape)


class Permute(KerasLayer):
    """dims are 1-based over non-batch axes (keras convention)."""

    def __init__(self, dims, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.dims = tuple(dims)

    def _call(self, params, state, x, training, rng):
        return jnp.transpose(x, (0,) + self.dims), state


class RepeatVector(KerasLayer):
    """(N, F) -> (N, n, F) (reference: nn/keras/RepeatVector.scala)."""

    def __init__(self, n, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.n = n

    def _call(self, params, state, x, training, rng):
        return jnp.repeat(x[:, None, :], self.n, axis=1), state


class Masking(KerasLayer):
    def __init__(self, mask_value=0.0, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.mask_value = mask_value

    def _build_labor(self, spec):
        return nn.Masking(self.mask_value)


class Highway(KerasLayer):
    # keras-1 Highway defaults to a LINEAR transform branch
    def __init__(self, activation=None, bias=True, input_shape=None,
                 name=None):
        super().__init__(input_shape, name)
        self._act_name = activation
        self.bias = bias

    def _build_labor(self, spec):
        # the activation applies to the transform branch only (inside
        # nn.Highway), not to the layer output
        return nn.Highway(spec.shape[-1], with_bias=self.bias,
                          activation=get_activation(self._act_name))


class MaxoutDense(KerasLayer):
    def __init__(self, output_dim, nb_feature=4, bias=True,
                 input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.output_dim = output_dim
        self.nb_feature = nb_feature

    def _build_labor(self, spec):
        return nn.Maxout(spec.shape[-1], self.output_dim, self.nb_feature)


class Embedding(KerasLayer):
    """(N, T) int -> (N, T, output_dim) (reference: nn/keras/Embedding.scala)."""

    def __init__(self, input_dim, output_dim, init="uniform",
                 input_shape=None, name=None, **_):
        super().__init__(input_shape, name)
        self.input_dim = input_dim
        self.output_dim = output_dim

    def _build_labor(self, spec):
        return nn.LookupTable(self.input_dim, self.output_dim)


class BatchNormalization(_Spatial):
    """reference: nn/keras/BatchNormalization.scala -- 2-D or 4-D input,
    normalises the channel axis."""

    def __init__(self, epsilon=1e-3, momentum=0.99, beta_init="zero",
                 gamma_init="one", dim_ordering="th", input_shape=None,
                 name=None, **_):
        super().__init__(dim_ordering, input_shape, name)
        self.epsilon = epsilon
        self.momentum = momentum

    def _build_labor(self, spec):
        n_out = spec.shape[-1]
        if len(spec.shape) == 2:
            return nn.BatchNormalization(n_out, eps=self.epsilon,
                                         momentum=1.0 - self.momentum)
        return nn.SpatialBatchNormalization(n_out, eps=self.epsilon,
                                            momentum=1.0 - self.momentum)


# ------------------------------------------------------------------ #
# convolution
# ------------------------------------------------------------------ #


class Convolution2D(_Spatial):
    """reference: nn/keras/Convolution2D.scala"""

    def __init__(self, nb_filter, nb_row, nb_col, init="glorot_uniform",
                 activation=None, border_mode="valid", subsample=(1, 1),
                 dim_ordering="th", bias=True, input_shape=None, name=None,
                 **_):
        super().__init__(dim_ordering, input_shape, name)
        self.nb_filter = nb_filter
        self.kernel = (nb_row, nb_col)
        self.subsample = _to_tuple(subsample)
        if border_mode not in ("valid", "same"):
            raise ValueError(f"border_mode {border_mode}")
        self.border_mode = border_mode
        self.bias = bias
        self.activation = get_activation(activation)

    def _build_labor(self, spec):
        kh, kw = self.kernel
        sh, sw = self.subsample
        if self.border_mode == "same":
            ph, pw = -1, -1     # nn.SpatialConvolution SAME convention
        else:
            ph, pw = 0, 0
        return nn.SpatialConvolution(
            spec.shape[-1], self.nb_filter, kw, kh, sw, sh, pw, ph,
            with_bias=self.bias)


class AtrousConvolution2D(_Spatial):
    """reference: nn/keras/AtrousConvolution2D.scala"""

    def __init__(self, nb_filter, nb_row, nb_col, init="glorot_uniform",
                 activation=None, subsample=(1, 1), atrous_rate=(1, 1),
                 dim_ordering="th", bias=True, input_shape=None, name=None,
                 **_):
        super().__init__(dim_ordering, input_shape, name)
        self.nb_filter = nb_filter
        self.kernel = (nb_row, nb_col)
        self.subsample = _to_tuple(subsample)
        self.atrous_rate = _to_tuple(atrous_rate)
        self.bias = bias
        self.activation = get_activation(activation)

    def _build_labor(self, spec):
        kh, kw = self.kernel
        sh, sw = self.subsample
        dh, dw = self.atrous_rate
        return nn.SpatialDilatedConvolution(
            spec.shape[-1], self.nb_filter, kw, kh, sw, sh, 0, 0, dw, dh,
            with_bias=self.bias)


class Convolution1D(KerasLayer):
    """(N, T, C) -> (N, T', nb_filter) (reference: nn/keras/Convolution1D.scala)."""

    def __init__(self, nb_filter, filter_length, init="glorot_uniform",
                 activation=None, border_mode="valid", subsample_length=1,
                 bias=True, input_shape=None, name=None, **_):
        super().__init__(input_shape, name)
        self.nb_filter = nb_filter
        self.filter_length = filter_length
        self.border_mode = border_mode
        self.subsample_length = subsample_length
        self.bias = bias
        self.activation = get_activation(activation)

    def _build_labor(self, spec):
        return nn.Conv1D(spec.shape[-1], self.nb_filter, self.filter_length,
                         stride_w=self.subsample_length,
                         pad_w=(-1 if self.border_mode == "same" else 0),
                         with_bias=self.bias)


class AtrousConvolution1D(KerasLayer):
    def __init__(self, nb_filter, filter_length, init="glorot_uniform",
                 activation=None, subsample_length=1, atrous_rate=1,
                 bias=True, input_shape=None, name=None, **_):
        super().__init__(input_shape, name)
        self.nb_filter = nb_filter
        self.filter_length = filter_length
        self.subsample_length = subsample_length
        self.atrous_rate = atrous_rate
        self.bias = bias
        self.activation = get_activation(activation)

    def _call(self, params, state, x, training, rng):
        y = lax.conv_general_dilated(
            x, params["weight"].astype(x.dtype), (self.subsample_length,),
            "VALID", rhs_dilation=(self.atrous_rate,),
            dimension_numbers=("NWC", "WIO", "NWC"))
        if self.bias:
            y = y + params["bias"].astype(y.dtype)
        return y, state

    def setup(self, rng, input_spec):
        from bigdl_tpu.nn.initialization import Xavier, Zeros
        cin = input_spec.shape[-1]
        k = self.filter_length
        w = Xavier().init(child_rng(rng, 0), (k, cin, self.nb_filter),
                          cin * k, self.nb_filter * k)
        p = {"weight": w}
        if self.bias:
            p["bias"] = Zeros().init(child_rng(rng, 1), (self.nb_filter,),
                                     cin, self.nb_filter)
        return p, ()


class Convolution3D(_Spatial):
    """reference: nn/keras/Convolution3D.scala (th: N,C,D,H,W)."""

    def __init__(self, nb_filter, kernel_dim1, kernel_dim2, kernel_dim3,
                 init="glorot_uniform", activation=None,
                 border_mode="valid", subsample=(1, 1, 1),
                 dim_ordering="th", bias=True, input_shape=None, name=None,
                 **_):
        super().__init__(dim_ordering, input_shape, name)
        self.nb_filter = nb_filter
        self.kernel = (kernel_dim1, kernel_dim2, kernel_dim3)
        self.subsample = _to_tuple(subsample, 3)
        self.border_mode = border_mode
        self.bias = bias
        self.activation = get_activation(activation)

    def _build_labor(self, spec):
        kt, kh, kw = self.kernel
        st, sh, sw = self.subsample
        return nn.VolumetricConvolution(
            spec.shape[-1], self.nb_filter, kt, kw, kh, st, sw, sh,
            with_bias=self.bias)


class Deconvolution2D(_Spatial):
    """reference: nn/keras/Deconvolution2D.scala"""

    def __init__(self, nb_filter, nb_row, nb_col, output_shape=None,
                 init="glorot_uniform", activation=None, subsample=(1, 1),
                 dim_ordering="th", bias=True, input_shape=None, name=None,
                 **_):
        super().__init__(dim_ordering, input_shape, name)
        self.nb_filter = nb_filter
        self.kernel = (nb_row, nb_col)
        self.subsample = _to_tuple(subsample)
        self.bias = bias
        self.activation = get_activation(activation)

    def _build_labor(self, spec):
        kh, kw = self.kernel
        sh, sw = self.subsample
        return nn.SpatialFullConvolution(
            spec.shape[-1], self.nb_filter, kw, kh, sw, sh,
            with_bias=self.bias)


class SeparableConvolution2D(_Spatial):
    """reference: nn/keras/SeparableConvolution2D.scala"""

    def __init__(self, nb_filter, nb_row, nb_col, init="glorot_uniform",
                 activation=None, border_mode="valid", subsample=(1, 1),
                 depth_multiplier=1, dim_ordering="th", bias=True,
                 input_shape=None, name=None, **_):
        super().__init__(dim_ordering, input_shape, name)
        self.nb_filter = nb_filter
        self.kernel = (nb_row, nb_col)
        self.subsample = _to_tuple(subsample)
        self.depth_multiplier = depth_multiplier
        self.border_mode = border_mode
        self.bias = bias
        self.activation = get_activation(activation)

    def _build_labor(self, spec):
        kh, kw = self.kernel
        sh, sw = self.subsample
        pad = -1 if self.border_mode == "same" else 0
        return nn.SpatialSeparableConvolution(
            spec.shape[-1], self.nb_filter, self.depth_multiplier,
            kw, kh, sw, sh, pad, pad, with_bias=self.bias)


class LocallyConnected1D(KerasLayer):
    def __init__(self, nb_filter, filter_length, activation=None,
                 subsample_length=1, bias=True, input_shape=None,
                 name=None, **_):
        super().__init__(input_shape, name)
        self.nb_filter = nb_filter
        self.filter_length = filter_length
        self.subsample_length = subsample_length
        self.bias = bias
        self.activation = get_activation(activation)

    def _build_labor(self, spec):
        return nn.LocallyConnected1D(
            spec.shape[1], spec.shape[2], self.nb_filter,
            self.filter_length, self.subsample_length,
            with_bias=self.bias)


class LocallyConnected2D(_Spatial):
    def __init__(self, nb_filter, nb_row, nb_col, activation=None,
                 border_mode="valid", subsample=(1, 1), dim_ordering="th",
                 bias=True, input_shape=None, name=None, **_):
        super().__init__(dim_ordering, input_shape, name)
        self.nb_filter = nb_filter
        self.kernel = (nb_row, nb_col)
        self.subsample = _to_tuple(subsample)
        self.bias = bias
        self.activation = get_activation(activation)

    def _build_labor(self, spec):
        kh, kw = self.kernel
        sh, sw = self.subsample
        return nn.LocallyConnected2D(
            spec.shape[3], spec.shape[2], spec.shape[1], self.nb_filter,
            kw, kh, sw, sh, with_bias=self.bias)


# ------------------------------------------------------------------ #
# pooling
# ------------------------------------------------------------------ #


class _Pool2D(_Spatial):
    def __init__(self, pool_size=(2, 2), strides=None, border_mode="valid",
                 dim_ordering="th", input_shape=None, name=None):
        super().__init__(dim_ordering, input_shape, name)
        self.pool_size = _to_tuple(pool_size)
        self.strides = _to_tuple(strides) if strides else self.pool_size
        self.border_mode = border_mode


class MaxPooling2D(_Pool2D):
    def _build_labor(self, spec):
        ph, pw = self.pool_size
        sh, sw = self.strides
        pad = -1 if self.border_mode == "same" else 0
        return nn.SpatialMaxPooling(pw, ph, sw, sh, pad, pad)


class AveragePooling2D(_Pool2D):
    def _build_labor(self, spec):
        ph, pw = self.pool_size
        sh, sw = self.strides
        pad = -1 if self.border_mode == "same" else 0
        return nn.SpatialAveragePooling(pw, ph, sw, sh, pad, pad)


class _Pool1D(KerasLayer):
    def __init__(self, pool_length=2, stride=None, border_mode="valid",
                 input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.pool_length = pool_length
        self.stride = stride or pool_length
        self.border_mode = border_mode

    def _reduce(self, x, init, op):
        pads = ((0, 0), (0, 0), (0, 0))
        if self.border_mode == "same":
            t = x.shape[1]
            out = -(-t // self.stride)
            need = max((out - 1) * self.stride + self.pool_length - t, 0)
            pads = ((0, 0), (need // 2, need - need // 2), (0, 0))
        return lax.reduce_window(x, init, op, (1, self.pool_length, 1),
                                 (1, self.stride, 1), pads)


class MaxPooling1D(_Pool1D):
    def _call(self, params, state, x, training, rng):
        return self._reduce(x, -jnp.inf, lax.max), state


class AveragePooling1D(_Pool1D):
    def _call(self, params, state, x, training, rng):
        s = self._reduce(x, 0.0, lax.add)
        n = self._reduce(jnp.ones_like(x), 0.0, lax.add)
        return s / n, state


class _Pool3D(_Spatial):
    def __init__(self, pool_size=(2, 2, 2), strides=None,
                 border_mode="valid", dim_ordering="th", input_shape=None,
                 name=None):
        super().__init__(dim_ordering, input_shape, name)
        self.pool_size = _to_tuple(pool_size, 3)
        self.strides = _to_tuple(strides, 3) if strides else self.pool_size


class MaxPooling3D(_Pool3D):
    def _build_labor(self, spec):
        pt, ph, pw = self.pool_size
        st, sh, sw = self.strides
        return nn.VolumetricMaxPooling(pt, pw, ph, st, sw, sh)


class AveragePooling3D(_Pool3D):
    def _build_labor(self, spec):
        pt, ph, pw = self.pool_size
        st, sh, sw = self.strides
        return nn.VolumetricAveragePooling(pt, pw, ph, st, sw, sh)


class GlobalMaxPooling1D(KerasLayer):
    def _call(self, params, state, x, training, rng):
        return jnp.max(x, axis=1), state


class GlobalAveragePooling1D(KerasLayer):
    def _call(self, params, state, x, training, rng):
        return jnp.mean(x, axis=1), state


class GlobalMaxPooling2D(_Spatial):
    def _call(self, params, state, x, training, rng):
        return self._nfirst_identity(jnp.max(x, axis=(1, 2))), state

    @staticmethod
    def _nfirst_identity(x):
        return x

    def apply(self, params, state, input, *, training=False, rng=None):
        x = self._nlast(input)
        return jnp.max(x, axis=(1, 2)), state


class GlobalAveragePooling2D(_Spatial):
    def apply(self, params, state, input, *, training=False, rng=None):
        x = self._nlast(input)
        return jnp.mean(x, axis=(1, 2)), state


class GlobalMaxPooling3D(_Spatial):
    def apply(self, params, state, input, *, training=False, rng=None):
        x = self._nlast(input)
        return jnp.max(x, axis=(1, 2, 3)), state


class GlobalAveragePooling3D(_Spatial):
    def apply(self, params, state, input, *, training=False, rng=None):
        x = self._nlast(input)
        return jnp.mean(x, axis=(1, 2, 3)), state


# ------------------------------------------------------------------ #
# padding / cropping / upsampling
# ------------------------------------------------------------------ #


class ZeroPadding1D(KerasLayer):
    def __init__(self, padding=1, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.padding = _to_tuple(padding, 2) if isinstance(
            padding, (tuple, list)) else (padding, padding)

    def _call(self, params, state, x, training, rng):
        lo, hi = self.padding
        return jnp.pad(x, ((0, 0), (lo, hi), (0, 0))), state


class ZeroPadding2D(_Spatial):
    def __init__(self, padding=(1, 1), dim_ordering="th", input_shape=None,
                 name=None):
        super().__init__(dim_ordering, input_shape, name)
        p = tuple(padding)
        self.pads = (p[0], p[0], p[1], p[1]) if len(p) == 2 else p

    def apply(self, params, state, input, *, training=False, rng=None):
        t, b, l, r = self.pads
        x = self._nlast(input)
        y = jnp.pad(x, ((0, 0), (t, b), (l, r), (0, 0)))
        return self._nfirst(y), state


class ZeroPadding3D(_Spatial):
    def __init__(self, padding=(1, 1, 1), dim_ordering="th",
                 input_shape=None, name=None):
        super().__init__(dim_ordering, input_shape, name)
        self.padding = _to_tuple(padding, 3)

    def apply(self, params, state, input, *, training=False, rng=None):
        pt, ph, pw = self.padding
        x = self._nlast(input)
        y = jnp.pad(x, ((0, 0), (pt, pt), (ph, ph), (pw, pw), (0, 0)))
        return self._nfirst(y), state


class Cropping1D(KerasLayer):
    def __init__(self, cropping=(1, 1), input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.cropping = tuple(cropping)

    def _call(self, params, state, x, training, rng):
        lo, hi = self.cropping
        return x[:, lo:x.shape[1] - hi], state


class Cropping2D(_Spatial):
    def __init__(self, cropping=((0, 0), (0, 0)), dim_ordering="th",
                 input_shape=None, name=None):
        super().__init__(dim_ordering, input_shape, name)
        self.cropping = tuple(tuple(c) for c in cropping)

    def apply(self, params, state, input, *, training=False, rng=None):
        (t, b), (l, r) = self.cropping
        x = self._nlast(input)
        y = x[:, t:x.shape[1] - b, l:x.shape[2] - r]
        return self._nfirst(y), state


class Cropping3D(_Spatial):
    def __init__(self, cropping=((1, 1), (1, 1), (1, 1)), dim_ordering="th",
                 input_shape=None, name=None):
        super().__init__(dim_ordering, input_shape, name)
        self.cropping = tuple(tuple(c) for c in cropping)

    def apply(self, params, state, input, *, training=False, rng=None):
        (a1, a2), (b1, b2), (c1, c2) = self.cropping
        x = self._nlast(input)
        y = x[:, a1:x.shape[1] - a2, b1:x.shape[2] - b2,
              c1:x.shape[3] - c2]
        return self._nfirst(y), state


class UpSampling1D(KerasLayer):
    def __init__(self, length=2, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.length = length

    def _call(self, params, state, x, training, rng):
        return jnp.repeat(x, self.length, axis=1), state


class UpSampling2D(_Spatial):
    def __init__(self, size=(2, 2), dim_ordering="th", input_shape=None,
                 name=None):
        super().__init__(dim_ordering, input_shape, name)
        self.size = _to_tuple(size)

    def apply(self, params, state, input, *, training=False, rng=None):
        x = self._nlast(input)
        y = jnp.repeat(jnp.repeat(x, self.size[0], 1), self.size[1], 2)
        return self._nfirst(y), state


class UpSampling3D(_Spatial):
    def __init__(self, size=(2, 2, 2), dim_ordering="th", input_shape=None,
                 name=None):
        super().__init__(dim_ordering, input_shape, name)
        self.size = _to_tuple(size, 3)

    def apply(self, params, state, input, *, training=False, rng=None):
        x = self._nlast(input)
        y = x
        for ax, s in enumerate(self.size):
            y = jnp.repeat(y, s, axis=ax + 1)
        return self._nfirst(y), state


# ------------------------------------------------------------------ #
# recurrent
# ------------------------------------------------------------------ #


class _KerasRNN(KerasLayer):
    def __init__(self, output_dim, activation="tanh", return_sequences=False,
                 go_backwards=False, input_shape=None, name=None, **_):
        super().__init__(input_shape, name)
        self.output_dim = output_dim
        self.return_sequences = return_sequences
        self.go_backwards = go_backwards
        self._act_name = activation

    def _make_cell(self, input_size):
        raise NotImplementedError

    def _build_labor(self, spec):
        return nn.Recurrent(self._make_cell(spec.shape[-1]),
                            reverse=self.go_backwards)

    def apply(self, params, state, input, *, training=False, rng=None):
        y, state = self._labor.apply(params, state, input,
                                     training=training, rng=rng)
        if not self.return_sequences:
            y = y[:, -1]
        return y, state


class SimpleRNN(_KerasRNN):
    def _make_cell(self, input_size):
        act = {"tanh": jnp.tanh, "relu": jax.nn.relu,
               "sigmoid": jax.nn.sigmoid}[self._act_name]
        return nn.RnnCell(input_size, self.output_dim, activation=act)


class LSTM(_KerasRNN):
    def _make_cell(self, input_size):
        return nn.LSTM(input_size, self.output_dim)


class GRU(_KerasRNN):
    """keras-1 GRU applies the reset gate BEFORE the recurrent matmul
    (reset_after=False); keras-2/3 default to reset_after=True."""

    def __init__(self, output_dim, activation="tanh", return_sequences=False,
                 go_backwards=False, reset_after=False, input_shape=None,
                 name=None, **kw):
        super().__init__(output_dim, activation, return_sequences,
                         go_backwards, input_shape, name, **kw)
        self.reset_after = reset_after

    def _make_cell(self, input_size):
        return nn.GRU(input_size, self.output_dim,
                      reset_after=self.reset_after)


class ConvLSTM2D(_Spatial):
    """reference: nn/keras/ConvLSTM2D.scala (th input N,T,C,H,W)."""

    def __init__(self, nb_filter, nb_kernel, activation="tanh",
                 dim_ordering="th", border_mode="valid", subsample=(1, 1),
                 return_sequences=False, go_backwards=False,
                 input_shape=None, name=None, **_):
        super().__init__(dim_ordering, input_shape, name)
        self.nb_filter = nb_filter
        self.nb_kernel = nb_kernel
        self.return_sequences = return_sequences
        self.go_backwards = go_backwards

    # the ConvLSTMPeephole cell is NCHW per step, so the canonical internal
    # layout is th (N, T, C, H, W); tf inputs are transposed at the boundary
    def _spec_th(self, spec):
        if self.dim_ordering == "th":
            return spec
        n, t, h, w, c = spec.shape
        return jax.ShapeDtypeStruct((n, t, c, h, w), spec.dtype)

    def setup(self, rng, input_spec):
        spec = self._spec_th(input_spec)
        self._labor = self._build_labor(spec)
        return self._labor.setup(rng, spec)

    def _build_labor(self, spec):
        cell = nn.ConvLSTMPeephole(
            spec.shape[2], self.nb_filter, self.nb_kernel, self.nb_kernel,
            with_peephole=False)
        return nn.Recurrent(cell, reverse=self.go_backwards)

    def apply(self, params, state, input, *, training=False, rng=None):
        x = input
        if self.dim_ordering == "tf":
            x = jnp.transpose(x, (0, 1, 4, 2, 3))
        y, state = self._labor.apply(params, state, x,
                                     training=training, rng=rng)
        if not self.return_sequences:
            y = y[:, -1]
        if self.dim_ordering == "tf":
            y = jnp.transpose(y, (0, 1, 3, 4, 2)) if y.ndim == 5 \
                else jnp.transpose(y, (0, 2, 3, 1))
        return y, state


class Bidirectional(KerasLayer):
    """Wrapper over a _KerasRNN (reference: nn/keras/Bidirectional.scala)."""

    def __init__(self, layer, merge_mode="concat", input_shape=None,
                 name=None):
        super().__init__(input_shape or layer.input_shape, name)
        self.layer = layer
        self.merge_mode = merge_mode

    def _build_labor(self, spec):
        fwd = self.layer._make_cell(spec.shape[-1])
        bwd = self.layer._make_cell(spec.shape[-1])
        return nn.BiRecurrent(fwd, bwd, merge=self.merge_mode)

    def apply(self, params, state, input, *, training=False, rng=None):
        y, state = self._labor.apply(params, state, input,
                                     training=training, rng=rng)
        if not self.layer.return_sequences:
            y = y[:, -1]
        return y, state


class TimeDistributed(KerasLayer):
    """Apply a layer to every timestep (reference: nn/keras/TimeDistributed.scala)."""

    def __init__(self, layer, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.layer = layer

    def _build_labor(self, spec):
        return nn.TimeDistributed(self.layer)


# ------------------------------------------------------------------ #
# advanced activations / noise
# ------------------------------------------------------------------ #


class ReLUVariant(KerasLayer):
    """keras-2/3 standalone ReLU with max_value / negative_slope /
    threshold (e.g. ReLU6 in MobileNet configs):
    f(x) = min(x, max_value) for x >= threshold,
    negative_slope * (x - threshold) otherwise."""

    def __init__(self, max_value=None, negative_slope=0.0, threshold=0.0,
                 input_shape=None, name=None, **_):
        super().__init__(input_shape, name)
        self.max_value = max_value
        self.negative_slope = negative_slope or 0.0
        self.threshold = threshold or 0.0

    def _call(self, params, state, x, training, rng):
        y = jnp.where(x >= self.threshold, x,
                      self.negative_slope * (x - self.threshold))
        if self.max_value is not None:
            y = jnp.minimum(y, self.max_value)
        return y.astype(x.dtype), state


class LeakyReLU(KerasLayer):
    def __init__(self, alpha=0.3, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.alpha = alpha

    def _build_labor(self, spec):
        return nn.LeakyReLU(self.alpha)


class ELU(KerasLayer):
    def __init__(self, alpha=1.0, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.alpha = alpha

    def _build_labor(self, spec):
        return nn.ELU(self.alpha)


class PReLU(KerasLayer):
    def __init__(self, input_shape=None, name=None):
        super().__init__(input_shape, name)

    def _build_labor(self, spec):
        # per-channel alphas (channel = last axis); matches keras PReLU on
        # dense inputs and keras shared_axes=spatial on conv inputs
        return nn.PReLU(spec.shape[-1])


class SReLU(KerasLayer):
    def __init__(self, input_shape=None, name=None, **_):
        super().__init__(input_shape, name)

    def _build_labor(self, spec):
        return nn.SReLU()


class ThresholdedReLU(KerasLayer):
    def __init__(self, theta=1.0, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.theta = theta

    def _build_labor(self, spec):
        return nn.Threshold(self.theta, 0.0)


class SoftMax(KerasLayer):
    def __init__(self, axis=-1, input_shape=None, name=None, **_):
        super().__init__(input_shape, name)
        self.axis = axis

    def _build_labor(self, spec):
        return nn.SoftMax(axis=self.axis)


class GaussianDropout(KerasLayer):
    def __init__(self, p, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.p = p

    def _build_labor(self, spec):
        return nn.GaussianDropout(self.p)


class GaussianNoise(KerasLayer):
    def __init__(self, sigma, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.sigma = sigma

    def _build_labor(self, spec):
        return nn.GaussianNoise(self.sigma)


class SpatialDropout1D(KerasLayer):
    def __init__(self, p=0.5, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.p = p

    def _build_labor(self, spec):
        return nn.SpatialDropout1D(self.p)


class SpatialDropout2D(_Spatial):
    def __init__(self, p=0.5, dim_ordering="th", input_shape=None,
                 name=None):
        super().__init__(dim_ordering, input_shape, name)
        self.p = p

    def _build_labor(self, spec):
        return nn.SpatialDropout2D(self.p)


class SpatialDropout3D(_Spatial):
    def __init__(self, p=0.5, dim_ordering="th", input_shape=None,
                 name=None):
        super().__init__(dim_ordering, input_shape, name)
        self.p = p

    def _build_labor(self, spec):
        return nn.SpatialDropout3D(self.p)


# ------------------------------------------------------------------ #
# merge
# ------------------------------------------------------------------ #


class Merge(KerasLayer):
    """Merge a table of inputs (reference: nn/keras/Merge.scala).
    mode: sum/mul/max/ave/concat/dot/cos."""

    def __init__(self, layers=None, mode="sum", concat_axis=-1,
                 input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.mode = mode
        self.concat_axis = concat_axis
        self.layers = layers or []

    def _call(self, params, state, xs, training, rng):
        m = self.mode
        if m == "sum":
            y = sum(xs[1:], xs[0])
        elif m == "mul":
            y = xs[0]
            for x in xs[1:]:
                y = y * x
        elif m == "max":
            y = xs[0]
            for x in xs[1:]:
                y = jnp.maximum(y, x)
        elif m == "ave":
            y = sum(xs[1:], xs[0]) / len(xs)
        elif m == "concat":
            y = jnp.concatenate(xs, axis=self.concat_axis)
        elif m == "dot":
            y = jnp.sum(xs[0] * xs[1], axis=-1, keepdims=True)
        elif m == "cos":
            a, b = xs[0], xs[1]
            na = jnp.linalg.norm(a, axis=-1, keepdims=True)
            nb = jnp.linalg.norm(b, axis=-1, keepdims=True)
            y = jnp.sum(a * b, -1, keepdims=True) / (na * nb + 1e-8)
        else:
            raise ValueError(f"unknown merge mode {m}")
        return y, state
