"""Keras model importer: JSON definition + HDF5 weights -> bigdl_tpu.

Reference: pyspark/bigdl/keras/converter.py (DefinitionLoader /
WeightLoader, 1759 LoC) -- consumes Keras-1.2.2 ``model.to_json()`` plus
``save_weights`` HDF5.  This importer reads the same 1.2.2 format and
additionally normalises Keras-2/3 config names (units->output_dim,
filters/kernel_size->nb_filter/nb_row/nb_col, padding->border_mode,
data_format->dim_ordering) so models written by modern Keras load too.

    model = load_keras(json_path="m.json", hdf5_path="m_weights.h5")
"""

import json

import numpy as np

import jax.numpy as jnp

from bigdl_tpu.keras import layers as KL
from bigdl_tpu.keras import topology as KT

# ------------------------------------------------------------------ #
# config normalisation (Keras 2/3 -> Keras 1.2.2 argument names)
# ------------------------------------------------------------------ #

_K2_CLASS = {
    "Conv1D": "Convolution1D",
    "Conv2D": "Convolution2D",
    "Conv3D": "Convolution3D",
    "Conv2DTranspose": "Deconvolution2D",
    "SeparableConv2D": "SeparableConvolution2D",
    "Add": "Merge", "Multiply": "Merge", "Average": "Merge",
    "Maximum": "Merge", "Concatenate": "Merge", "Dot": "Merge",
}

_K2_MERGE_MODE = {
    "Add": "sum", "Multiply": "mul", "Average": "ave", "Maximum": "max",
    "Concatenate": "concat", "Dot": "dot",
}


def _norm_config(class_name, cfg):
    """-> (keras1 class name, keras1-style config dict)."""
    cfg = dict(cfg)
    out = {}
    name = _K2_CLASS.get(class_name, class_name)

    def mv(src, dst, f=lambda v: v):
        if src in cfg and cfg[src] is not None:
            out[dst] = f(cfg.pop(src))

    mv("name", "name")
    mv("batch_input_shape", "batch_input_shape")
    mv("batch_shape", "batch_input_shape")       # keras3 InputLayer
    if "input_shape" in cfg:
        out.setdefault("batch_input_shape",
                       [None] + list(cfg.pop("input_shape")))
    mv("units", "output_dim")                    # Dense/RNN keras2
    mv("output_dim", "output_dim")
    mv("filters", "nb_filter")
    mv("nb_filter", "nb_filter")
    if "kernel_size" in cfg:
        ks = cfg.pop("kernel_size")
        ks = list(ks) if isinstance(ks, (list, tuple)) else [ks]
        if name == "Convolution1D":
            out["filter_length"] = ks[0]
        elif name == "Convolution3D":
            out["kernel_dim1"], out["kernel_dim2"], out["kernel_dim3"] = ks
        else:
            out["nb_row"], out["nb_col"] = ks[0], ks[-1]
    for k in ("nb_row", "nb_col", "filter_length", "kernel_dim1",
              "kernel_dim2", "kernel_dim3"):
        mv(k, k)
    if "strides" in cfg:
        st = cfg.pop("strides")
        st = list(st) if isinstance(st, (list, tuple)) else [st]
        if name in ("Convolution1D", "MaxPooling1D", "AveragePooling1D"):
            out["subsample_length" if name == "Convolution1D"
                else "stride"] = st[0]
        else:
            out["subsample"] = tuple(st)
    mv("subsample", "subsample", tuple)
    mv("subsample_length", "subsample_length")
    if "padding" in cfg and isinstance(cfg["padding"], str):
        out["border_mode"] = cfg.pop("padding")
    elif "padding" in cfg:
        out["padding"] = cfg.pop("padding")      # ZeroPadding layers
    mv("border_mode", "border_mode")
    if "data_format" in cfg:
        out["dim_ordering"] = ("tf" if cfg.pop("data_format")
                               == "channels_last" else "th")
    mv("dim_ordering", "dim_ordering")
    mv("use_bias", "bias")
    mv("bias", "bias")
    if "activation" in cfg:
        act = cfg.pop("activation")
        if isinstance(act, dict):                # keras3 serialized object
            act = act.get("config", {}).get("name", act.get("class_name"))
        out["activation"] = act
    mv("pool_size", "pool_size", tuple)
    mv("pool_length", "pool_length")
    mv("stride", "stride")
    mv("rate", "p")                              # Dropout keras2
    mv("p", "p")
    mv("dropout", "p")
    mv("epsilon", "epsilon")
    mv("momentum", "momentum")
    mv("axis", "axis")
    if isinstance(out.get("axis"), (list, tuple)):   # tf.keras ListWrapper
        out["axis"] = out["axis"][0] if out["axis"] else -1
    mv("nb_feature", "nb_feature")
    mv("max_value", "max_value")
    mv("negative_slope", "negative_slope")
    mv("threshold", "threshold")
    mv("reset_after", "reset_after")
    mv("input_dim", "input_dim")
    mv("input_length", "input_length")
    mv("target_shape", "target_shape", tuple)
    mv("dims", "dims", tuple)
    mv("n", "n")
    mv("size", "size", tuple)
    mv("length", "length")
    mv("cropping", "cropping")
    mv("mask_value", "mask_value")
    mv("alpha", "alpha")
    mv("theta", "theta")
    mv("sigma", "sigma")
    mv("stddev", "sigma")                        # GaussianNoise keras2
    mv("return_sequences", "return_sequences")
    mv("go_backwards", "go_backwards")
    mv("merge_mode", "merge_mode")
    mv("layer", "layer")                         # wrapper inner-layer config
    mv("mode", "mode")
    mv("concat_axis", "concat_axis")
    if class_name in _K2_MERGE_MODE:
        out["mode"] = _K2_MERGE_MODE[class_name]
        if class_name == "Concatenate":
            # mv("axis") above already moved the key into out
            out["concat_axis"] = out.pop("axis", cfg.pop("axis", -1))
    return name, out


_BUILDERS = {
    "Dense": lambda c: KL.Dense(
        c["output_dim"], activation=c.get("activation"),
        bias=c.get("bias", True)),
    "Activation": lambda c: KL.Activation(c["activation"]),
    "Dropout": lambda c: KL.Dropout(c.get("p", 0.5)),
    "Flatten": lambda c: KL.Flatten(),
    "Reshape": lambda c: KL.Reshape(c["target_shape"]),
    "Permute": lambda c: KL.Permute(c["dims"]),
    "RepeatVector": lambda c: KL.RepeatVector(c["n"]),
    "Masking": lambda c: KL.Masking(c.get("mask_value", 0.0)),
    "Highway": lambda c: KL.Highway(
        activation=c.get("activation"), bias=c.get("bias", True)),
    "MaxoutDense": lambda c: KL.MaxoutDense(
        c["output_dim"], c.get("nb_feature", 4)),
    "LocallyConnected1D": lambda c: KL.LocallyConnected1D(
        c["nb_filter"], c["filter_length"],
        activation=c.get("activation"),
        subsample_length=c.get("subsample_length", 1),
        bias=c.get("bias", True)),
    "LocallyConnected2D": lambda c: KL.LocallyConnected2D(
        c["nb_filter"], c["nb_row"], c["nb_col"],
        activation=c.get("activation"),
        subsample=c.get("subsample", (1, 1)),
        dim_ordering=c.get("dim_ordering", "th"),
        bias=c.get("bias", True)),
    "Embedding": lambda c: KL.Embedding(c["input_dim"], c["output_dim"]),
    "BatchNormalization": lambda c: KL.BatchNormalization(
        epsilon=c.get("epsilon", 1e-3), momentum=c.get("momentum", 0.99),
        # keras2/3 carry the channel axis instead of dim_ordering:
        # axis=-1/ndim-1 is channels-last ("tf"), axis=1 channels-first
        dim_ordering=("tf" if c.get("axis", None) in (-1, 3, 4)
                      else c.get("dim_ordering", "th"))),
    "Convolution1D": lambda c: KL.Convolution1D(
        c["nb_filter"], c["filter_length"],
        activation=c.get("activation"),
        border_mode=c.get("border_mode", "valid"),
        subsample_length=c.get("subsample_length", 1),
        bias=c.get("bias", True)),
    "Convolution2D": lambda c: KL.Convolution2D(
        c["nb_filter"], c["nb_row"], c["nb_col"],
        activation=c.get("activation"),
        border_mode=c.get("border_mode", "valid"),
        subsample=c.get("subsample", (1, 1)),
        dim_ordering=c.get("dim_ordering", "th"),
        bias=c.get("bias", True)),
    "Convolution3D": lambda c: KL.Convolution3D(
        c["nb_filter"], c["kernel_dim1"], c["kernel_dim2"],
        c["kernel_dim3"], activation=c.get("activation"),
        border_mode=c.get("border_mode", "valid"),
        subsample=c.get("subsample", (1, 1, 1)),
        dim_ordering=c.get("dim_ordering", "th"),
        bias=c.get("bias", True)),
    "Deconvolution2D": lambda c: KL.Deconvolution2D(
        c["nb_filter"], c["nb_row"], c["nb_col"],
        activation=c.get("activation"),
        subsample=c.get("subsample", (1, 1)),
        dim_ordering=c.get("dim_ordering", "th"),
        bias=c.get("bias", True)),
    "SeparableConvolution2D": lambda c: KL.SeparableConvolution2D(
        c["nb_filter"], c["nb_row"], c["nb_col"],
        activation=c.get("activation"),
        border_mode=c.get("border_mode", "valid"),
        subsample=c.get("subsample", (1, 1)),
        depth_multiplier=c.get("depth_multiplier", 1),
        dim_ordering=c.get("dim_ordering", "th"),
        bias=c.get("bias", True)),
    "MaxPooling1D": lambda c: KL.MaxPooling1D(
        c.get("pool_length", 2), c.get("stride"),
        c.get("border_mode", "valid")),
    "AveragePooling1D": lambda c: KL.AveragePooling1D(
        c.get("pool_length", 2), c.get("stride"),
        c.get("border_mode", "valid")),
    "MaxPooling2D": lambda c: KL.MaxPooling2D(
        c.get("pool_size", (2, 2)), c.get("strides"),
        c.get("border_mode", "valid"), c.get("dim_ordering", "th")),
    "AveragePooling2D": lambda c: KL.AveragePooling2D(
        c.get("pool_size", (2, 2)), c.get("strides"),
        c.get("border_mode", "valid"), c.get("dim_ordering", "th")),
    "MaxPooling3D": lambda c: KL.MaxPooling3D(
        c.get("pool_size", (2, 2, 2)), c.get("strides"),
        c.get("border_mode", "valid"), c.get("dim_ordering", "th")),
    "AveragePooling3D": lambda c: KL.AveragePooling3D(
        c.get("pool_size", (2, 2, 2)), c.get("strides"),
        c.get("border_mode", "valid"), c.get("dim_ordering", "th")),
    "GlobalMaxPooling1D": lambda c: KL.GlobalMaxPooling1D(),
    "GlobalAveragePooling1D": lambda c: KL.GlobalAveragePooling1D(),
    "GlobalMaxPooling2D": lambda c: KL.GlobalMaxPooling2D(
        c.get("dim_ordering", "th")),
    "GlobalAveragePooling2D": lambda c: KL.GlobalAveragePooling2D(
        c.get("dim_ordering", "th")),
    "GlobalMaxPooling3D": lambda c: KL.GlobalMaxPooling3D(
        c.get("dim_ordering", "th")),
    "GlobalAveragePooling3D": lambda c: KL.GlobalAveragePooling3D(
        c.get("dim_ordering", "th")),
    "ZeroPadding1D": lambda c: KL.ZeroPadding1D(c.get("padding", 1)),
    "ZeroPadding2D": lambda c: KL.ZeroPadding2D(
        c.get("padding", (1, 1)), c.get("dim_ordering", "th")),
    "ZeroPadding3D": lambda c: KL.ZeroPadding3D(
        c.get("padding", (1, 1, 1)), c.get("dim_ordering", "th")),
    "Cropping1D": lambda c: KL.Cropping1D(c.get("cropping", (1, 1))),
    "Cropping2D": lambda c: KL.Cropping2D(
        c.get("cropping", ((0, 0), (0, 0))), c.get("dim_ordering", "th")),
    "Cropping3D": lambda c: KL.Cropping3D(
        c.get("cropping", ((1, 1), (1, 1), (1, 1))),
        c.get("dim_ordering", "th")),
    "UpSampling1D": lambda c: KL.UpSampling1D(c.get("length", 2)),
    "UpSampling2D": lambda c: KL.UpSampling2D(
        c.get("size", (2, 2)), c.get("dim_ordering", "th")),
    "UpSampling3D": lambda c: KL.UpSampling3D(
        c.get("size", (2, 2, 2)), c.get("dim_ordering", "th")),
    "SimpleRNN": lambda c: KL.SimpleRNN(
        c["output_dim"], c.get("activation", "tanh"),
        c.get("return_sequences", False), c.get("go_backwards", False)),
    "LSTM": lambda c: KL.LSTM(
        c["output_dim"], c.get("activation", "tanh"),
        c.get("return_sequences", False), c.get("go_backwards", False)),
    "GRU": lambda c: KL.GRU(
        c["output_dim"], c.get("activation", "tanh"),
        c.get("return_sequences", False), c.get("go_backwards", False),
        # keras1 configs have no reset_after key -> False (its convention)
        reset_after=c.get("reset_after", False)),
    "LeakyReLU": lambda c: KL.LeakyReLU(c.get("alpha", 0.3)),
    "ELU": lambda c: KL.ELU(c.get("alpha", 1.0)),
    "PReLU": lambda c: KL.PReLU(),
    "SReLU": lambda c: KL.SReLU(),
    "ThresholdedReLU": lambda c: KL.ThresholdedReLU(c.get("theta", 1.0)),
    "SoftMax": lambda c: KL.SoftMax(),
    "GaussianDropout": lambda c: KL.GaussianDropout(c.get("p", 0.5)),
    "GaussianNoise": lambda c: KL.GaussianNoise(c.get("sigma", 0.1)),
    "SpatialDropout1D": lambda c: KL.SpatialDropout1D(c.get("p", 0.5)),
    "SpatialDropout2D": lambda c: KL.SpatialDropout2D(
        c.get("p", 0.5), c.get("dim_ordering", "th")),
    "SpatialDropout3D": lambda c: KL.SpatialDropout3D(
        c.get("p", 0.5), c.get("dim_ordering", "th")),
    "Merge": lambda c: KL.Merge(
        mode=c.get("mode", "sum"), concat_axis=c.get("concat_axis", -1)),
    "ConvLSTM2D": lambda c: KL.ConvLSTM2D(
        c["nb_filter"], c.get("nb_row", 3),
        dim_ordering=c.get("dim_ordering", "th"),
        return_sequences=c.get("return_sequences", False),
        go_backwards=c.get("go_backwards", False)),
    "Bidirectional": lambda c: KL.Bidirectional(
        _inner_layer(c), merge_mode=c.get("merge_mode", "concat")),
    "TimeDistributed": lambda c: KL.TimeDistributed(_inner_layer(c)),
    # keras-2/3 standalone activation layers (ReLU keeps max_value /
    # negative_slope / threshold -- e.g. ReLU6 in MobileNet configs)
    "ReLU": lambda c: (
        KL.Activation("relu")
        if c.get("max_value") is None and not c.get("negative_slope")
        and not c.get("threshold")
        else KL.ReLUVariant(c.get("max_value"),
                            c.get("negative_slope", 0.0),
                            c.get("threshold", 0.0))),
    "Softmax": lambda c: KL.SoftMax(axis=c.get("axis", -1)),
}


def _inner_layer(cfg):
    """Wrapper configs (Bidirectional/TimeDistributed) nest the wrapped
    layer as {"class_name": ..., "config": ...}."""
    inner = cfg["layer"]
    layer, _ = _build_layer(inner["class_name"], inner["config"])
    return layer


def _build_layer(class_name, raw_config):
    name, cfg = _norm_config(class_name, raw_config)
    if name in ("InputLayer", "Input"):
        return None, cfg
    if name not in _BUILDERS:
        raise NotImplementedError(
            f"keras importer: unsupported layer {class_name}")
    layer = _BUILDERS[name](cfg)
    if cfg.get("name"):
        layer.name = cfg["name"]
    if cfg.get("batch_input_shape"):
        layer.input_shape = tuple(cfg["batch_input_shape"][1:])
    layer._keras_class = name
    layer._keras_config = cfg
    return layer, cfg


def model_from_json(text):
    """Keras model JSON (1.2.2 or 2/3) -> bigdl_tpu keras model."""
    spec = json.loads(text) if isinstance(text, str) else text
    cls = spec["class_name"]
    config = spec["config"]
    if cls == "Sequential":
        layer_confs = config["layers"] if isinstance(config, dict) \
            else config    # keras1: list; keras2/3: {"layers": [...]}
        model = KT.Sequential()
        for lc in layer_confs:
            layer, cfg = _build_layer(lc["class_name"], lc["config"])
            if layer is None:      # InputLayer: record shape for the next
                model._pending_input_shape = tuple(
                    cfg["batch_input_shape"][1:])
                continue
            if getattr(model, "_pending_input_shape", None) is not None \
                    and layer.input_shape is None:
                layer.input_shape = model._pending_input_shape
                model._pending_input_shape = None
            model.add(layer)
        return model
    if cls in ("Model", "Functional"):
        return _model_from_functional(config)
    raise NotImplementedError(f"unsupported model class {cls}")


def _model_from_functional(config):
    nodes = {}       # layer name -> output Node
    layers = {}
    for lc in config["layers"]:
        lname = lc.get("name") or lc["config"].get("name")
        layer, cfg = _build_layer(lc["class_name"], lc["config"])
        inbound = lc.get("inbound_nodes") or []
        in_names = _inbound_names(inbound)
        if layer is None:
            node = KT.Input(shape=cfg["batch_input_shape"][1:])
            nodes[lname] = node
            continue
        layers[lname] = layer
        parents = [nodes[n] for n in in_names]
        nodes[lname] = layer(*parents)
    def top(names):
        # keras3 writes a single output as one flat [name, idx, tensor] triple
        if (isinstance(names, (list, tuple)) and len(names) == 3
                and isinstance(names[0], str)
                and not isinstance(names[1], (list, tuple, str))):
            names = [names]
        return [nodes[n[0] if isinstance(n, (list, tuple)) else n]
                for n in names]
    inputs = top(config["input_layers"])
    outputs = top(config["output_layers"])
    return KT.Model(inputs, outputs)


def _inbound_names(inbound):
    """keras1/2: [[[name, idx, tensor_idx], ...]]; keras3: list of dicts."""
    if not inbound:
        return []
    first = inbound[0]
    if isinstance(first, dict):      # keras3
        hist = first["args"][0]
        hist = hist if isinstance(hist, list) else [hist]
        out = []
        for h in hist:
            kh = h["config"]["keras_history"] if isinstance(h, dict) else h
            out.append(kh[0])
        return out
    return [e[0] for e in first]


# ------------------------------------------------------------------ #
# weight install
# ------------------------------------------------------------------ #


def _param_dicts(tree, keys=("weight",)):
    """All dicts in the subtree containing every key, traversal order."""
    found = []

    def walk(t):
        if isinstance(t, dict):
            if all(k in t for k in keys):
                found.append(t)
            for k in sorted(t):
                walk(t[k])
        elif isinstance(t, (tuple, list)):
            for v in t:
                walk(v)
    walk(tree)
    return found


def _as_mutable(tree):
    if isinstance(tree, dict):
        return {k: _as_mutable(v) for k, v in tree.items()}
    if isinstance(tree, tuple):
        return tuple(_as_mutable(v) for v in tree)
    if isinstance(tree, list):
        return [_as_mutable(v) for v in tree]
    return tree


def _set(d, key, arr):
    want = tuple(np.shape(d[key]))
    got = tuple(np.shape(arr))
    if want != got:
        raise ValueError(f"weight shape mismatch for {key}: model {want} "
                         f"vs file {got}")
    d[key] = jnp.asarray(np.asarray(arr, np.float32))


def _install_dense(layer, p, s, arrays):
    W = arrays[0]
    d = _param_dicts(p)[0]
    _set(d, "weight", W.T)
    if len(arrays) > 1:
        _set(d, "bias", arrays[1])


def _install_conv2d(layer, p, s, arrays):
    W = arrays[0]
    if W.ndim == 4 and W.shape[-1] != layer.nb_filter:
        # keras1 th layout (nb_filter, stack, rows, cols) -> HWIO
        W = W.transpose(2, 3, 1, 0)
    d = _param_dicts(p)[0]
    _set(d, "weight", W.reshape(np.shape(d["weight"])))
    if len(arrays) > 1:
        _set(d, "bias", arrays[1])


def _install_conv1d(layer, p, s, arrays):
    W = arrays[0]
    d = _param_dicts(p)[0]
    if W.ndim == 4:                  # keras1 stores (k, 1, cin, cout)
        W = W.reshape(W.shape[0], W.shape[2], W.shape[3])
    _set(d, "weight", W.reshape(np.shape(d["weight"])))
    if len(arrays) > 1:
        _set(d, "bias", arrays[1])


def _install_bn(layer, p, s, arrays):
    gamma, beta, mean, var = arrays
    d = _param_dicts(p)[0]
    _set(d, "weight", gamma)
    _set(d, "bias", beta)
    sd = _param_dicts(s, keys=("running_mean",))[0]
    _set(sd, "running_mean", mean)
    _set(sd, "running_var", var)


def _install_embedding(layer, p, s, arrays):
    _set(_param_dicts(p)[0], "weight", arrays[0])


def _split_rnn(arrays, n_gates):
    """keras1 stores per-gate (W, U, b)*gates; keras2/3 stores
    (kernel, recurrent_kernel, bias)."""
    if len(arrays) == 3:
        W, U, b = arrays
        Ws = np.split(W, n_gates, axis=1)
        Us = np.split(U, n_gates, axis=1)
        bs = np.split(b, n_gates, axis=-1)
        if b.ndim == 2:              # keras3 GRU bias (2, 3h)
            bs = [x for x in np.split(b[0], n_gates)]
        return Ws, Us, bs
    Ws = arrays[0::3]
    Us = arrays[1::3]
    bs = arrays[2::3]
    return list(Ws), list(Us), list(bs)


def _install_lstm(layer, p, s, arrays):
    Ws, Us, bs = _split_rnn(arrays, 4)
    if len(arrays) == 3:
        order = [0, 1, 2, 3]         # keras2/3: i, f, c, o
    else:
        order = [0, 2, 1, 3]         # keras1 file: i, c, f, o -> i,f,c,o
    # ours: gate order i, f, g(c), o with (4h, in) weights
    idx = {"ifco": order}
    W = np.concatenate([Ws[i] for i in ([0, 1, 2, 3] if len(arrays) == 3
                                        else [0, 2, 1, 3])], axis=1)
    U = np.concatenate([Us[i] for i in ([0, 1, 2, 3] if len(arrays) == 3
                                        else [0, 2, 1, 3])], axis=1)
    b = np.concatenate([bs[i] for i in ([0, 1, 2, 3] if len(arrays) == 3
                                        else [0, 2, 1, 3])], axis=-1)
    d = _param_dicts(p, keys=("weight_ih",))[0]
    _set(d, "weight_ih", W.T)
    _set(d, "weight_hh", U.T)
    _set(d, "bias_ih", b.reshape(-1))
    _set(d, "bias_hh", np.zeros_like(b.reshape(-1)))


def _install_gru(layer, p, s, arrays):
    """Our GRU cell follows the reset-after convention
    (n = tanh(Wx + b_i + r*(Uh + b_h)), nn/recurrent.py GRU.step), which is
    keras GRU reset_after=True (the keras-2/3 default; its bias is (2, 3h))."""
    perm = [1, 0, 2]                 # keras order z, r, h; ours r, z, n
    if len(arrays) == 3:
        W, U, b = (np.asarray(a) for a in arrays)
        Ws = np.split(W, 3, axis=1)
        Us = np.split(U, 3, axis=1)
        if b.ndim == 2:              # reset_after=True
            bi, bh = b[0], b[1]
        else:                        # reset_after=False: no recurrent bias
            bi, bh = b, np.zeros_like(b)
        bis, bhs = np.split(bi, 3), np.split(bh, 3)
    else:                            # keras1 per-gate (W, U, b) * 3
        Ws, Us, bis = arrays[0::3], arrays[1::3], arrays[2::3]
        bhs = [np.zeros_like(np.asarray(x).reshape(-1)) for x in bis]
    W = np.concatenate([Ws[i] for i in perm], axis=1)
    U = np.concatenate([Us[i] for i in perm], axis=1)
    bi = np.concatenate([np.asarray(bis[i]).reshape(-1) for i in perm])
    bh = np.concatenate([np.asarray(bhs[i]).reshape(-1) for i in perm])
    d = _param_dicts(p, keys=("weight_ih",))[0]
    _set(d, "weight_ih", W.T)
    _set(d, "weight_hh", U.T)
    _set(d, "bias_ih", bi)
    _set(d, "bias_hh", bh)


def _install_simple_rnn(layer, p, s, arrays):
    W, U, b = arrays
    d = _param_dicts(p, keys=("weight_ih",))[0]
    _set(d, "weight_ih", W.T)
    _set(d, "weight_hh", U.T)
    _set(d, "bias_ih", np.asarray(b).reshape(-1))
    _set(d, "bias_hh", np.zeros_like(np.asarray(b).reshape(-1)))


def _install_prelu(layer, p, s, arrays):
    """keras alpha has shape input_shape[1:] (shared axes already 1);
    ours is a flat per-channel (or shared scalar) vector."""
    alpha = np.asarray(arrays[0]).reshape(-1) \
        if np.asarray(arrays[0]).ndim <= 1 else None
    if alpha is None:
        a = np.asarray(arrays[0])
        # conv input: accept only channel-wise alphas (spatial axes shared)
        lead = a.reshape(-1, a.shape[-1])
        if not np.allclose(lead, lead[0]):
            raise ValueError("PReLU alphas vary over spatial axes; "
                             "bigdl_tpu PReLU is per-channel only")
        alpha = lead[0]
    d = _param_dicts(p)[0]
    if np.shape(d["weight"]) == (1,) and alpha.size > 1:
        if not np.allclose(alpha, alpha[0]):
            raise ValueError("shared PReLU cannot hold per-channel alphas")
        alpha = alpha[:1]
    _set(d, "weight", alpha)


def _install_srelu(layer, p, s, arrays):
    """keras SReLU get_weights order: t_left, a_left, t_right, a_right."""
    d = _param_dicts(p, keys=("t_left",))[0]
    for key, arr in zip(("t_left", "a_left", "t_right", "a_right"), arrays):
        _set(d, key, arr)


def _install_maxout(layer, p, s, arrays):
    """keras1 MaxoutDense: W (nb_feature, input_dim, output_dim) -- its
    build computes np.dot(x, W) which contracts x's last axis with W's
    SECOND-TO-LAST axis -- and b (nb_feature, output_dim).  Ours: weight
    (nb*out, in) with row m*output_size + o <-> W[m, :, o] (nn.Maxout
    reshapes to (maxout_number, output_size) before the max)."""
    W = np.asarray(arrays[0])
    d = _param_dicts(p)[0]
    _set(d, "weight", W.transpose(0, 2, 1).reshape(-1, W.shape[1]))
    if len(arrays) > 1:
        _set(d, "bias", np.asarray(arrays[1]).reshape(-1))


def _install_highway(layer, p, s, arrays):
    """keras1 Highway get_weights: W, W_carry, b, b_carry with
    y = act(xW+b)*sigmoid(xWc+bc) + x*(1-sigmoid(...)); ours stores
    transposed (out, in) w_h/w_t."""
    d = _param_dicts(p, keys=("w_t",))[0]
    _set(d, "w_h", np.asarray(arrays[0]).T)
    _set(d, "w_t", np.asarray(arrays[1]).T)
    if len(arrays) > 2:
        _set(d, "b_h", arrays[2])
        _set(d, "b_t", arrays[3])


def _install_local1d(layer, p, s, arrays):
    """keras LocallyConnected1D kernel (out_t, k*cin, filters), bias
    (out_t, filters) -- identical layout to ours."""
    d = _param_dicts(p)[0]
    _set(d, "weight", arrays[0])
    if len(arrays) > 1:
        _set(d, "bias", arrays[1])


def _install_local2d(layer, p, s, arrays):
    """keras LocallyConnected2D kernel (oh*ow, kh*kw*cin, filters) with
    (kh, kw, cin)-major patch order; ours (oh, ow, cin*kh*kw, cout) because
    lax.conv_general_dilated_patches emits channel-major patches."""
    lab = getattr(layer, "_labor", layer)
    kh, kw = lab.kernel
    cin, f = lab.cin, lab.cout
    oh, ow = lab._out_hw()
    W = np.asarray(arrays[0]).reshape(oh * ow, kh, kw, cin, f)
    W = W.transpose(0, 3, 1, 2, 4).reshape(oh, ow, cin * kh * kw, f)
    d = _param_dicts(p)[0]
    _set(d, "weight", W)
    if len(arrays) > 1:
        _set(d, "bias", np.asarray(arrays[1]).reshape(np.shape(d["bias"])))


def _install_convlstm2d(layer, p, s, arrays):
    """keras ConvLSTM2D: kernel (kh, kw, cin, 4f), recurrent (kh, kw, f, 4f),
    bias (4f,), gate order i,f,c,o == our i,f,g,o; ours is OIHW."""
    K, U = np.asarray(arrays[0]), np.asarray(arrays[1])
    d = _param_dicts(p, keys=("weight_ih",))[0]
    _set(d, "weight_ih", K.transpose(3, 2, 0, 1))
    _set(d, "weight_hh", U.transpose(3, 2, 0, 1))
    if len(arrays) > 2:
        _set(d, "bias", np.asarray(arrays[2]).reshape(-1))


def _install_bidirectional(layer, p, s, arrays):
    """keras Bidirectional get_weights = forward layer's arrays then the
    backward layer's; our BiRecurrent params are {"fwd": ..., "bwd": ...}.
    """
    inner_cls = getattr(layer.layer, "_keras_class",
                        type(layer.layer).__name__)
    installer = _INSTALLERS[inner_cls]
    half = len(arrays) // 2
    installer(layer.layer, p["fwd"], s, arrays[:half])
    installer(layer.layer, p["bwd"], s, arrays[half:])


def _install_time_distributed(layer, p, s, arrays):
    inner_cls = getattr(layer.layer, "_keras_class",
                        type(layer.layer).__name__)
    _INSTALLERS[inner_cls](layer.layer, p, s, arrays)


_INSTALLERS = {
    "Bidirectional": _install_bidirectional,
    "TimeDistributed": _install_time_distributed,
    "Dense": _install_dense,
    "Convolution2D": _install_conv2d,
    "Deconvolution2D": _install_conv2d,
    "Convolution1D": _install_conv1d,
    "BatchNormalization": _install_bn,
    "Embedding": _install_embedding,
    "LSTM": _install_lstm,
    "GRU": _install_gru,
    "SimpleRNN": _install_simple_rnn,
    "PReLU": _install_prelu,
    "SReLU": _install_srelu,
    "MaxoutDense": _install_maxout,
    "Highway": _install_highway,
    "LocallyConnected1D": _install_local1d,
    "LocallyConnected2D": _install_local2d,
    "ConvLSTM2D": _install_convlstm2d,
}


def set_layer_weights(model, weights_by_layer):
    """Install keras weight arrays into a BUILT Sequential model.

    weights_by_layer: list aligned with model.modules of (arrays or None).
    """
    if not model.is_built():
        model.build_model()
    p = _as_mutable(model._params)
    st = _as_mutable(model._state)
    for i, (layer, arrays) in enumerate(zip(model.modules,
                                            weights_by_layer)):
        if not arrays:
            continue
        cls = getattr(layer, "_keras_class", type(layer).__name__)
        installer = _INSTALLERS.get(cls)
        if installer is None:
            raise NotImplementedError(
                f"no weight installer for keras layer {cls}")
        installer(layer, p[str(i)], st[str(i)],
                  [np.asarray(a) for a in arrays])
    model._params = p
    model._state = st
    return model


def set_graph_weights(model, weights_by_name):
    """Install keras weight arrays into a BUILT functional Model.

    weights_by_name: dict of layer name -> arrays.  Graph params are keyed
    by topological index (nn/graph.py setup), so walk ``model._topo``.
    """
    if not model.is_built():
        model.build_model()
    p = _as_mutable(model._params)
    st = _as_mutable(model._state)
    for i, node in enumerate(model._topo):
        layer = node.module
        if layer is None:
            continue
        arrays = weights_by_name.get(layer.name)
        if not arrays:
            continue
        cls = getattr(layer, "_keras_class", type(layer).__name__)
        installer = _INSTALLERS.get(cls)
        if installer is None:
            raise NotImplementedError(
                f"no weight installer for keras layer {cls}")
        installer(layer, p[str(i)], st[str(i)],
                  [np.asarray(a) for a in arrays])
    model._params = p
    model._state = st
    return model


def _read_weights_h5_v3(path):
    """Keras 3 ``.weights.h5`` layout: layers/<auto>/.../vars/<int>, with
    the USER layer name in the vars group's 'name' attr.  -> dict user
    name -> [arrays in vars order] (matches get_weights order)."""
    import h5py

    by_name = {}
    with h5py.File(path, "r") as f:
        layers = f["layers"] if "layers" in f else f.get("_layer_checkpoint_dependencies")
        if layers is None:
            raise ValueError(f"{path}: no 'layers' group (not a keras-3 "
                             f"weights file)")

        def gather(group):
            """All vars datasets under this layer group, traversal order."""
            arrays = []
            # the layer's own vars group carries the USER name; nested
            # cell/vars groups carry internal names (e.g. 'lstm_cell')
            name = None
            if "vars" in group:
                name = group["vars"].attrs.get("name")

            def visit(g):
                nonlocal name
                for k in g:
                    item = g[k]
                    if isinstance(item, h5py.Group):
                        if k == "vars" and name is None:
                            name = item.attrs.get("name")
                        visit(item)
                    elif g.name.rsplit("/", 1)[-1] == "vars":
                        arrays.append((g.name, int(k), np.asarray(item)))
            visit(group)
            if isinstance(name, bytes):
                name = name.decode()
            arrays.sort(key=lambda t: (t[0], t[1]))
            return name, [a for _, _, a in arrays]

        for key in layers:
            name, arrays = gather(layers[key])
            if arrays:
                by_name[name or key] = arrays
    return by_name


def load_weights_hdf5(model, path, by_name=False):
    """Keras HDF5 weight files: the legacy save_weights 1.x/2.x layout
    (attrs['layer_names'] + per-group attrs['weight_names']) and the
    keras-3 ``.weights.h5`` layout (layers/<auto>/vars/<int>)."""
    import h5py

    with h5py.File(path, "r") as f:
        g = f["model_weights"] if "model_weights" in f else f
        if "layer_names" not in g.attrs:
            by_layer_name = _read_weights_h5_v3(path)
            from bigdl_tpu.nn.graph import Graph

            if isinstance(model, Graph):
                return set_graph_weights(model, by_layer_name)
            if not model.is_built():
                model.build_model()
            import jax

            has_params = [bool(jax.tree.leaves(
                model._params.get(str(i), ()))) for i in
                range(len(model.modules))]
            named = all(layer.name in by_layer_name
                        for layer, hp in zip(model.modules, has_params)
                        if hp)
            ordered = list(by_layer_name.values())
            weights, qi = [], 0
            for layer, hp in zip(model.modules, has_params):
                if not hp:
                    weights.append(None)
                elif named:
                    weights.append(by_layer_name[layer.name])
                else:                        # positional: param-bearing only
                    weights.append(ordered[qi] if qi < len(ordered)
                                   else None)
                    qi += 1
            return set_layer_weights(model, weights)
        layer_names = [n.decode() if isinstance(n, bytes) else n
                       for n in g.attrs["layer_names"]]
        by_layer_name = {}
        for ln in layer_names:
            grp = g[ln]
            wnames = [n.decode() if isinstance(n, bytes) else n
                      for n in grp.attrs.get("weight_names", [])]
            by_layer_name[ln] = [np.asarray(grp[w]) for w in wnames]
    from bigdl_tpu.nn.graph import Graph

    if isinstance(model, Graph):
        # functional Model: params are keyed by topo index, match by name
        return set_graph_weights(model, by_layer_name)
    weights = []
    for layer in model.modules:
        arrays = by_layer_name.get(layer.name)
        if arrays is None and not by_name:
            # positional fallback: consume file layers in order
            for ln in layer_names:
                if by_layer_name.get(ln):
                    arrays = by_layer_name.pop(ln)
                    break
        weights.append(arrays)
    return set_layer_weights(model, weights)


def load_keras(json_path=None, hdf5_path=None, json_str=None):
    """Reference API: bigdl.keras.converter.load_keras(json, hdf5)."""
    if json_str is None:
        with open(json_path) as f:
            json_str = f.read()
    model = model_from_json(json_str)
    model.build_model()
    if hdf5_path:
        load_weights_hdf5(model, hdf5_path)
    return model
