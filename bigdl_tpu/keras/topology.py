"""Keras topology: Sequential / Model / Input with shape inference.

Reference: nn/keras/Topology.scala (Sequential :262, Model :165) and
nn/keras/Input.scala.  compile/fit/evaluate/predict come from the existing
training mixin (nn/keras.py); this module adds the Keras-side shape
bookkeeping: ``input_shape`` on the first layer, ``get_output_shape()``,
and eager build so weight shapes exist as soon as the model is assembled
(matching the reference, which builds each KerasLayer at add() time).
"""

import numpy as np

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.graph import Node
from bigdl_tpu.nn.keras import _KerasMixin
from bigdl_tpu.keras.layers import KerasLayer


def Input(shape=None, name=None, dtype=jnp.float32):
    """Graph input node carrying its (batch-less) shape
    (reference: nn/keras/Input.scala)."""
    node = Node(None, [])
    node.keras_shape = tuple(shape) if shape is not None else None
    node.keras_dtype = dtype
    return node


class Sequential(_KerasMixin, nn.Sequential):
    """Keras Sequential with shape inference at add() time
    (reference: Topology.scala:262)."""

    def __init__(self, name=None):
        super().__init__(name)
        self._shapes = []      # output spec after each layer (with batch=1)

    def add(self, layer):
        if not self.modules:
            in_shape = getattr(layer, "input_shape", None)
            if in_shape is not None:
                self._shapes = [jax.ShapeDtypeStruct(
                    (1,) + tuple(in_shape), jnp.float32)]
        super().add(layer)
        if self._shapes:
            # infer this layer's output spec eagerly (reference builds the
            # labor at add() time; here eval_shape costs no compute)
            spec = self._shapes[-1]
            p, s = layer.setup(jax.random.key(0), spec)
            self._shapes.append(layer.output_spec(p, s, spec))
        return self

    def get_input_shape(self):
        assert self._shapes, "first layer needs input_shape"
        return (None,) + tuple(self._shapes[0].shape[1:])

    def get_output_shape(self):
        assert self._shapes, "first layer needs input_shape"
        return (None,) + tuple(self._shapes[-1].shape[1:])

    def build_model(self, dtype=jnp.float32):
        """Materialise params from the recorded input_shape."""
        assert self._shapes, "first layer needs input_shape"
        spec = jax.ShapeDtypeStruct(self._shapes[0].shape, dtype)
        self.build(spec)
        return self


class Model(_KerasMixin, nn.Graph):
    """Keras functional Model over Input() nodes
    (reference: Topology.scala:165)."""

    def __init__(self, input, output, name=None):
        inputs = input if isinstance(input, (list, tuple)) else [input]
        outputs = output if isinstance(output, (list, tuple)) else [output]
        super().__init__(list(inputs), list(outputs), name)
        self._input_specs = [
            jax.ShapeDtypeStruct((1,) + tuple(n.keras_shape),
                                 getattr(n, "keras_dtype", jnp.float32))
            for n in inputs if getattr(n, "keras_shape", None) is not None]

    def get_input_shape(self):
        assert self._input_specs, "Input(shape=...) required"
        if len(self._input_specs) == 1:
            return (None,) + tuple(self._input_specs[0].shape[1:])
        return [(None,) + tuple(s.shape[1:]) for s in self._input_specs]

    def get_output_shape(self):
        spec = self._input_specs
        spec = spec[0] if len(spec) == 1 else tuple(spec)
        p, s = self.setup(jax.random.key(0), spec)
        out = self.output_spec(p, s, spec)
        if isinstance(out, tuple):
            return [(None,) + tuple(o.shape[1:]) for o in out]
        return (None,) + tuple(out.shape[1:])

    def build_model(self, dtype=jnp.float32):
        assert self._input_specs, "Input(shape=...) required"
        spec = [jax.ShapeDtypeStruct(s.shape, dtype)
                for s in self._input_specs]
        self.build(spec[0] if len(spec) == 1 else tuple(spec))
        return self
