"""Vision pipeline: ImageFeature/ImageFrame + composable augmentations.

Reference: transform/vision/image/ImageFeature.scala:36 (hash-map of stages),
ImageFrame.scala (local/distributed containers), FeatureTransformer.scala
(composable augs), augmentation/ (18 transforms: Resize, CenterCrop,
RandomCrop, HFlip, ChannelNormalize, Brightness, Contrast, Saturation,
PixelNormalizer, RandomTransformer, ...).

Host-side (CPU) numpy implementations -- TPUs don't decode images
(SURVEY.md section 2.8: keep the image pipeline pure host-side).  Layout
HWC float32; the pipeline ends in Samples feeding SampleToMiniBatch.
"""

from typing import Callable, Dict, List, Optional

import numpy as np

from bigdl_tpu.dataset.minibatch import Sample


class ImageFeature(dict):
    """Mutable per-image state dict (reference: ImageFeature.scala:36).

    Well-known keys: 'image' (HWC float32), 'label', 'path',
    'original_size'.
    """

    def __init__(self, image=None, label=None, path=None):
        super().__init__()
        if image is not None:
            self["image"] = np.asarray(image, np.float32)
            self["original_size"] = self["image"].shape
        if label is not None:
            self["label"] = label
        if path is not None:
            self["path"] = path

    @property
    def image(self):
        return self["image"]

    @image.setter
    def image(self, v):
        self["image"] = v


class FeatureTransformer:
    """Composable ImageFeature -> ImageFeature stage
    (reference: FeatureTransformer.scala; compose with ``>>``)."""

    def transform(self, feature: ImageFeature) -> ImageFeature:
        raise NotImplementedError

    def __call__(self, feature):
        return self.transform(feature)

    def __rshift__(self, other):
        return _Chained(self, other)


class _Chained(FeatureTransformer):
    def __init__(self, a, b):
        self.a, self.b = a, b

    def transform(self, feature):
        return self.b(self.a(feature))


class Resize(FeatureTransformer):
    """Bilinear resize (reference: augmentation/Resize.scala)."""

    def __init__(self, height: int, width: int):
        self.h, self.w = height, width

    def transform(self, feature):
        feature["image"] = bilinear_resize(feature["image"], self.h, self.w)
        return feature


class AspectScale(FeatureTransformer):
    """Scale the short side to ``scale`` keeping aspect
    (reference: augmentation/AspectScale.scala)."""

    def __init__(self, scale: int, max_size: int = 1000):
        self.scale, self.max_size = scale, max_size

    def transform(self, feature):
        img = feature["image"]
        h, w = img.shape[:2]
        ratio = self.scale / min(h, w)
        if max(h, w) * ratio > self.max_size:
            ratio = self.max_size / max(h, w)
        feature["image"] = bilinear_resize(
            img, int(round(h * ratio)), int(round(w * ratio)))
        return feature


class CenterCrop(FeatureTransformer):
    def __init__(self, height: int, width: int):
        self.h, self.w = height, width

    def transform(self, feature):
        img = feature["image"]
        h, w = img.shape[:2]
        y0, x0 = (h - self.h) // 2, (w - self.w) // 2
        feature["image"] = img[y0:y0 + self.h, x0:x0 + self.w]
        return feature


class RandomCrop(FeatureTransformer):
    def __init__(self, height: int, width: int, seed: Optional[int] = None):
        self.h, self.w = height, width
        self.rng = np.random.default_rng(seed)

    def transform(self, feature):
        img = feature["image"]
        h, w = img.shape[:2]
        y0 = int(self.rng.integers(0, h - self.h + 1))
        x0 = int(self.rng.integers(0, w - self.w + 1))
        feature["image"] = img[y0:y0 + self.h, x0:x0 + self.w]
        return feature


class HFlip(FeatureTransformer):
    """Horizontal flip (reference: augmentation/HFlip.scala)."""

    def transform(self, feature):
        feature["image"] = feature["image"][:, ::-1]
        return feature


class RandomHFlip(FeatureTransformer):
    def __init__(self, prob=0.5, seed: Optional[int] = None):
        self.prob = prob
        self.rng = np.random.default_rng(seed)

    def transform(self, feature):
        if self.rng.random() < self.prob:
            feature["image"] = feature["image"][:, ::-1]
        return feature


class ChannelNormalize(FeatureTransformer):
    """(x - mean) / std per channel (reference: augmentation/ChannelNormalize.scala)."""

    def __init__(self, mean, std):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def transform(self, feature):
        feature["image"] = (feature["image"] - self.mean) / self.std
        return feature


class PixelNormalizer(FeatureTransformer):
    """Subtract a full mean image (reference: augmentation/PixelNormalizer.scala)."""

    def __init__(self, means: np.ndarray):
        self.means = np.asarray(means, np.float32)

    def transform(self, feature):
        feature["image"] = feature["image"] - self.means
        return feature


class Brightness(FeatureTransformer):
    def __init__(self, delta_low, delta_high, seed: Optional[int] = None):
        self.low, self.high = delta_low, delta_high
        self.rng = np.random.default_rng(seed)

    def transform(self, feature):
        feature["image"] = feature["image"] + self.rng.uniform(self.low,
                                                               self.high)
        return feature


class Contrast(FeatureTransformer):
    def __init__(self, delta_low, delta_high, seed: Optional[int] = None):
        self.low, self.high = delta_low, delta_high
        self.rng = np.random.default_rng(seed)

    def transform(self, feature):
        feature["image"] = feature["image"] * self.rng.uniform(self.low,
                                                               self.high)
        return feature


class Saturation(FeatureTransformer):
    """Blend with the grayscale image (reference: augmentation/Saturation.scala)."""

    def __init__(self, delta_low, delta_high, seed: Optional[int] = None):
        self.low, self.high = delta_low, delta_high
        self.rng = np.random.default_rng(seed)

    def transform(self, feature):
        img = feature["image"]
        gray = img.mean(axis=-1, keepdims=True)
        alpha = self.rng.uniform(self.low, self.high)
        feature["image"] = gray + alpha * (img - gray)
        return feature


class ChannelScaledNormalizer(FeatureTransformer):
    def __init__(self, scale: float):
        self.scale = scale

    def transform(self, feature):
        feature["image"] = feature["image"] * self.scale
        return feature


class RandomTransformer(FeatureTransformer):
    """Apply inner transformer with probability ``prob``
    (reference: augmentation/RandomTransformer.scala)."""

    def __init__(self, inner: FeatureTransformer, prob: float,
                 seed: Optional[int] = None):
        self.inner = inner
        self.prob = prob
        self.rng = np.random.default_rng(seed)

    def transform(self, feature):
        if self.rng.random() < self.prob:
            return self.inner(feature)
        return feature


class MatToSample(FeatureTransformer):
    """Terminal stage: ImageFeature -> Sample
    (reference: ImageFrameToSample / MatToTensor)."""

    def transform(self, feature):
        feature["sample"] = Sample(feature["image"], feature.get("label"))
        return feature


class ImageFrame:
    """Local collection of ImageFeatures (reference: ImageFrame.scala
    LocalImageFrame; the distributed variant shards like
    DistributedDataSet)."""

    def __init__(self, features: List[ImageFeature]):
        self.features = features

    @staticmethod
    def from_arrays(images, labels=None):
        labels = labels if labels is not None else [None] * len(images)
        return ImageFrame([ImageFeature(im, lb)
                           for im, lb in zip(images, labels)])

    def transform(self, transformer: FeatureTransformer) -> "ImageFrame":
        self.features = [transformer(f) for f in self.features]
        return self

    def __rshift__(self, transformer):
        return self.transform(transformer)

    def to_samples(self) -> List[Sample]:
        self.transform(MatToSample())
        return [f["sample"] for f in self.features]


def bilinear_resize(img: np.ndarray, out_h: int, out_w: int) -> np.ndarray:
    """Pure-numpy bilinear resize, align_corners=False (OpenCV-compatible
    sampling grid)."""
    h, w = img.shape[:2]
    if (h, w) == (out_h, out_w):
        return img
    ys = (np.arange(out_h) + 0.5) * h / out_h - 0.5
    xs = (np.arange(out_w) + 0.5) * w / out_w - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0.0, 1.0)[:, None, None]
    wx = np.clip(xs - x0, 0.0, 1.0)[None, :, None]
    img = img if img.ndim == 3 else img[..., None]
    top = img[y0][:, x0] * (1 - wx) + img[y0][:, x1] * wx
    bot = img[y1][:, x0] * (1 - wx) + img[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    return out.astype(np.float32)
