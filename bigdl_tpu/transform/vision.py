"""Vision pipeline: ImageFeature/ImageFrame + composable augmentations.

Reference: transform/vision/image/ImageFeature.scala:36 (hash-map of stages),
ImageFrame.scala (local/distributed containers), FeatureTransformer.scala
(composable augs), augmentation/ (18 transforms: Resize, CenterCrop,
RandomCrop, HFlip, ChannelNormalize, Brightness, Contrast, Saturation,
PixelNormalizer, RandomTransformer, ...).

Host-side (CPU) numpy implementations -- TPUs don't decode images
(SURVEY.md section 2.8: keep the image pipeline pure host-side).  Layout
HWC float32; the pipeline ends in Samples feeding SampleToMiniBatch.
"""

from typing import Callable, Dict, List, Optional

import numpy as np

from bigdl_tpu.dataset.minibatch import Sample


class ImageFeature(dict):
    """Mutable per-image state dict (reference: ImageFeature.scala:36).

    Well-known keys: 'image' (HWC float32), 'label', 'path',
    'original_size'.
    """

    def __init__(self, image=None, label=None, path=None):
        super().__init__()
        if image is not None:
            self["image"] = np.asarray(image, np.float32)
            self["original_size"] = self["image"].shape
        if label is not None:
            self["label"] = label
        if path is not None:
            self["path"] = path

    @property
    def image(self):
        return self["image"]

    @image.setter
    def image(self, v):
        self["image"] = v


class FeatureTransformer:
    """Composable ImageFeature -> ImageFeature stage
    (reference: FeatureTransformer.scala; compose with ``>>``)."""

    def transform(self, feature: ImageFeature) -> ImageFeature:
        raise NotImplementedError

    def __call__(self, feature):
        return self.transform(feature)

    def __rshift__(self, other):
        return _Chained(self, other)


class _Chained(FeatureTransformer):
    def __init__(self, a, b):
        self.a, self.b = a, b

    def transform(self, feature):
        return self.b(self.a(feature))


class Resize(FeatureTransformer):
    """Bilinear resize (reference: augmentation/Resize.scala)."""

    def __init__(self, height: int, width: int):
        self.h, self.w = height, width

    def transform(self, feature):
        feature["image"] = bilinear_resize(feature["image"], self.h, self.w)
        return feature


class AspectScale(FeatureTransformer):
    """Scale the short side to ``scale`` keeping aspect
    (reference: augmentation/AspectScale.scala)."""

    def __init__(self, scale: int, max_size: int = 1000):
        self.scale, self.max_size = scale, max_size

    def transform(self, feature):
        img = feature["image"]
        h, w = img.shape[:2]
        ratio = self.scale / min(h, w)
        if max(h, w) * ratio > self.max_size:
            ratio = self.max_size / max(h, w)
        feature["image"] = bilinear_resize(
            img, int(round(h * ratio)), int(round(w * ratio)))
        return feature


class CenterCrop(FeatureTransformer):
    def __init__(self, height: int, width: int):
        self.h, self.w = height, width

    def transform(self, feature):
        img = feature["image"]
        h, w = img.shape[:2]
        y0, x0 = (h - self.h) // 2, (w - self.w) // 2
        feature["image"] = img[y0:y0 + self.h, x0:x0 + self.w]
        return feature


class RandomCrop(FeatureTransformer):
    def __init__(self, height: int, width: int, seed: Optional[int] = None):
        self.h, self.w = height, width
        self.rng = np.random.default_rng(seed)

    def transform(self, feature):
        img = feature["image"]
        h, w = img.shape[:2]
        y0 = int(self.rng.integers(0, h - self.h + 1))
        x0 = int(self.rng.integers(0, w - self.w + 1))
        feature["image"] = img[y0:y0 + self.h, x0:x0 + self.w]
        return feature


class HFlip(FeatureTransformer):
    """Horizontal flip (reference: augmentation/HFlip.scala)."""

    def transform(self, feature):
        feature["image"] = feature["image"][:, ::-1]
        return feature


class RandomHFlip(FeatureTransformer):
    def __init__(self, prob=0.5, seed: Optional[int] = None):
        self.prob = prob
        self.rng = np.random.default_rng(seed)

    def transform(self, feature):
        if self.rng.random() < self.prob:
            feature["image"] = feature["image"][:, ::-1]
        return feature


class ChannelNormalize(FeatureTransformer):
    """(x - mean) / std per channel (reference: augmentation/ChannelNormalize.scala)."""

    def __init__(self, mean, std):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def transform(self, feature):
        feature["image"] = (feature["image"] - self.mean) / self.std
        return feature


class PixelNormalizer(FeatureTransformer):
    """Subtract a full mean image (reference: augmentation/PixelNormalizer.scala)."""

    def __init__(self, means: np.ndarray):
        self.means = np.asarray(means, np.float32)

    def transform(self, feature):
        feature["image"] = feature["image"] - self.means
        return feature


class Brightness(FeatureTransformer):
    def __init__(self, delta_low, delta_high, seed: Optional[int] = None):
        self.low, self.high = delta_low, delta_high
        self.rng = np.random.default_rng(seed)

    def transform(self, feature):
        feature["image"] = feature["image"] + self.rng.uniform(self.low,
                                                               self.high)
        return feature


class Contrast(FeatureTransformer):
    def __init__(self, delta_low, delta_high, seed: Optional[int] = None):
        self.low, self.high = delta_low, delta_high
        self.rng = np.random.default_rng(seed)

    def transform(self, feature):
        feature["image"] = feature["image"] * self.rng.uniform(self.low,
                                                               self.high)
        return feature


class Saturation(FeatureTransformer):
    """Blend with the grayscale image (reference: augmentation/Saturation.scala)."""

    def __init__(self, delta_low, delta_high, seed: Optional[int] = None):
        self.low, self.high = delta_low, delta_high
        self.rng = np.random.default_rng(seed)

    def transform(self, feature):
        img = feature["image"]
        gray = img.mean(axis=-1, keepdims=True)
        alpha = self.rng.uniform(self.low, self.high)
        feature["image"] = gray + alpha * (img - gray)
        return feature


class ChannelScaledNormalizer(FeatureTransformer):
    def __init__(self, scale: float):
        self.scale = scale

    def transform(self, feature):
        feature["image"] = feature["image"] * self.scale
        return feature


class RandomTransformer(FeatureTransformer):
    """Apply inner transformer with probability ``prob``
    (reference: augmentation/RandomTransformer.scala)."""

    def __init__(self, inner: FeatureTransformer, prob: float,
                 seed: Optional[int] = None):
        self.inner = inner
        self.prob = prob
        self.rng = np.random.default_rng(seed)

    def transform(self, feature):
        if self.rng.random() < self.prob:
            return self.inner(feature)
        return feature


class Expand(FeatureTransformer):
    """Place the image on a mean-filled larger canvas at a random offset,
    recording the inverse boundary box for RoiProject (reference:
    augmentation/Expand.scala -- SSD zoom-out augmentation)."""

    def __init__(self, means_r=123, means_g=117, means_b=104,
                 min_expand_ratio=1.0, max_expand_ratio=4.0,
                 seed: Optional[int] = None):
        self.means = np.asarray([means_r, means_g, means_b], np.float32)
        self.min_ratio, self.max_ratio = min_expand_ratio, max_expand_ratio
        self.rng = np.random.default_rng(seed)

    def transform(self, feature):
        if abs(self.max_ratio - 1.0) < 1e-2:
            return feature
        img = feature["image"]
        h, w = img.shape[:2]
        ratio = self.rng.uniform(self.min_ratio, self.max_ratio)
        nh, nw = int(h * ratio), int(w * ratio)
        h_off = float(np.floor(self.rng.uniform(0, nh - h)))
        w_off = float(np.floor(self.rng.uniform(0, nw - w)))
        canvas = np.tile(self.means, (nh, nw, 1)).astype(np.float32)
        canvas[int(h_off):int(h_off) + h, int(w_off):int(w_off) + w] = img
        feature["image"] = canvas
        if "label" in feature:
            from bigdl_tpu.transform.vision_roi import BoundingBox

            feature["bounding_box"] = BoundingBox(
                -w_off / w, -h_off / h, (nw - w_off) / w, (nh - h_off) / h)
        return feature


class Filler(FeatureTransformer):
    """Fill a normalized sub-rectangle with a constant (reference:
    augmentation/Filler.scala)."""

    def __init__(self, start_x, start_y, end_x, end_y, value=255):
        self.box = (start_x, start_y, end_x, end_y)
        self.value = value

    def transform(self, feature):
        img = feature["image"]
        h, w = img.shape[:2]
        x1, y1, x2, y2 = self.box
        img[int(y1 * h):int(y2 * h), int(x1 * w):int(x2 * w)] = self.value
        feature["image"] = img
        return feature


def _rgb_to_hsv(img):
    import colorsys  # noqa: F401 (documenting the convention)
    r, g, b = img[..., 0], img[..., 1], img[..., 2]
    maxc = img.max(-1)
    minc = img.min(-1)
    v = maxc
    span = np.where(maxc > 0, maxc - minc, 1.0)
    s = np.where(maxc > 0, (maxc - minc) / np.where(maxc == 0, 1, maxc), 0)
    rc = (maxc - r) / span
    gc = (maxc - g) / span
    bc = (maxc - b) / span
    h = np.where(maxc == minc, 0.0,
                 np.where(maxc == r, bc - gc,
                          np.where(maxc == g, 2.0 + rc - bc,
                                   4.0 + gc - rc)))
    h = (h / 6.0) % 1.0
    return h, s, v


def _hsv_to_rgb(h, s, v):
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1.0 - s)
    q = v * (1.0 - s * f)
    t = v * (1.0 - s * (1.0 - f))
    i = i.astype(np.int32) % 6
    conds = [i == k for k in range(6)]
    r = np.select(conds, [v, q, p, p, t, v])
    g = np.select(conds, [t, v, v, q, p, p])
    b = np.select(conds, [p, p, t, v, v, q])
    return np.stack([r, g, b], -1)


class Hue(FeatureTransformer):
    """Rotate the hue channel by a random angle in degrees (reference:
    augmentation/Hue.scala -- HSV-space hue shift)."""

    def __init__(self, delta_low=-18.0, delta_high=18.0,
                 seed: Optional[int] = None):
        self.low, self.high = delta_low, delta_high
        self.rng = np.random.default_rng(seed)

    def transform(self, feature):
        img = np.clip(feature["image"], 0, 255).astype(np.float32)
        scale = 255.0 if img.max() > 1.5 else 1.0
        h, s, v = _rgb_to_hsv(img / scale)
        delta = self.rng.uniform(self.low, self.high) / 360.0
        h = (h + delta) % 1.0
        feature["image"] = (_hsv_to_rgb(h, s, v) * scale).astype(np.float32)
        return feature


class ChannelOrder(FeatureTransformer):
    """Randomly permute the color channels (reference:
    augmentation/ChannelOrder.scala)."""

    def __init__(self, seed: Optional[int] = None):
        self.rng = np.random.default_rng(seed)

    def transform(self, feature):
        perm = self.rng.permutation(3)
        feature["image"] = np.ascontiguousarray(feature["image"][..., perm])
        return feature


class ColorJitter(FeatureTransformer):
    """Brightness/contrast/saturation/hue in random order (reference:
    augmentation/ColorJitter.scala)."""

    def __init__(self, brightness_prob=0.5, brightness_delta=32,
                 contrast_prob=0.5, contrast_lower=0.5, contrast_upper=1.5,
                 saturation_prob=0.5, saturation_lower=0.5,
                 saturation_upper=1.5, hue_prob=0.5, hue_delta=18,
                 seed: Optional[int] = None):
        rng = np.random.default_rng(seed)
        self.rng = rng
        self.stages = [
            RandomTransformer(
                Brightness(-brightness_delta, brightness_delta,
                           seed=int(rng.integers(1 << 31))),
                brightness_prob, seed=int(rng.integers(1 << 31))),
            RandomTransformer(
                Contrast(contrast_lower, contrast_upper,
                         seed=int(rng.integers(1 << 31))),
                contrast_prob, seed=int(rng.integers(1 << 31))),
            RandomTransformer(
                Saturation(saturation_lower, saturation_upper,
                           seed=int(rng.integers(1 << 31))),
                saturation_prob, seed=int(rng.integers(1 << 31))),
            RandomTransformer(
                Hue(-hue_delta, hue_delta, seed=int(rng.integers(1 << 31))),
                hue_prob, seed=int(rng.integers(1 << 31))),
        ]

    def transform(self, feature):
        for i in self.rng.permutation(len(self.stages)):
            feature = self.stages[i](feature)
        return feature


class RandomResize(FeatureTransformer):
    """Resize to a random scale from a list (reference:
    augmentation/RandomResize.scala)."""

    def __init__(self, min_size, max_size, seed: Optional[int] = None):
        self.min_size, self.max_size = min_size, max_size
        self.rng = np.random.default_rng(seed)

    def transform(self, feature):
        size = int(self.rng.integers(self.min_size, self.max_size + 1))
        return Resize(size, size)(feature)


class MTImageFeatureToBatch:
    """Parallel decode/augment/batch assembler (reference:
    MTImageFeatureToBatch.scala: multi-threaded ImageFeature -> MiniBatch
    with fixed output size; detection labels batch as a list of RoiLabels
    since box counts vary per image)."""

    def __init__(self, width, height, batch_size,
                 transformer: Optional[FeatureTransformer] = None,
                 to_rgb=False, extract_roi=False, num_threads=4):
        import threading

        self.width, self.height = width, height
        self.batch_size = batch_size
        self.transformer = transformer
        self.extract_roi = extract_roi
        self.num_threads = num_threads
        # np.random.Generator (inside the random augmentations) is not
        # thread-safe; the reference clones the transformer per thread
        # (MTImageFeatureToBatch.scala), here a lock serialises the cheap
        # augment stage while decode/resize stay parallel
        self._transform_lock = threading.Lock()

    def _one(self, feature):
        if self.transformer is not None:
            with self._transform_lock:
                feature = self.transformer(feature)
        img = feature["image"]
        if img.shape[:2] != (self.height, self.width):
            img = bilinear_resize(img, self.height, self.width)
        return img, feature.get("label")

    def __call__(self, features):
        """iterable of ImageFeature -> yields (images (B,H,W,3), labels)."""
        from concurrent.futures import ThreadPoolExecutor

        batch = []
        with ThreadPoolExecutor(self.num_threads) as pool:
            for img, label in pool.map(self._one, features):
                batch.append((img, label))
                if len(batch) == self.batch_size:
                    yield self._assemble(batch)
                    batch = []
        if batch:
            yield self._assemble(batch)

    def _assemble(self, batch):
        images = np.stack([b[0] for b in batch]).astype(np.float32)
        labels = [b[1] for b in batch]
        if self.extract_roi:
            return images, labels        # list of RoiLabel
        if all(l is not None and np.ndim(l) == 0 for l in labels):
            return images, np.asarray(labels)
        return images, labels


class MatToSample(FeatureTransformer):
    """Terminal stage: ImageFeature -> Sample
    (reference: ImageFrameToSample / MatToTensor)."""

    def transform(self, feature):
        feature["sample"] = Sample(feature["image"], feature.get("label"))
        return feature


class ImageFrame:
    """Local collection of ImageFeatures (reference: ImageFrame.scala
    LocalImageFrame; the distributed variant shards like
    DistributedDataSet)."""

    def __init__(self, features: List[ImageFeature]):
        self.features = features

    @staticmethod
    def from_arrays(images, labels=None):
        labels = labels if labels is not None else [None] * len(images)
        return ImageFrame([ImageFeature(im, lb)
                           for im, lb in zip(images, labels)])

    def transform(self, transformer: FeatureTransformer) -> "ImageFrame":
        self.features = [transformer(f) for f in self.features]
        return self

    def __rshift__(self, transformer):
        return self.transform(transformer)

    def to_samples(self) -> List[Sample]:
        self.transform(MatToSample())
        return [f["sample"] for f in self.features]


def bilinear_resize(img: np.ndarray, out_h: int, out_w: int) -> np.ndarray:
    """Pure-numpy bilinear resize, align_corners=False (OpenCV-compatible
    sampling grid)."""
    h, w = img.shape[:2]
    if (h, w) == (out_h, out_w):
        return img
    ys = (np.arange(out_h) + 0.5) * h / out_h - 0.5
    xs = (np.arange(out_w) + 0.5) * w / out_w - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0.0, 1.0)[:, None, None]
    wx = np.clip(xs - x0, 0.0, 1.0)[None, :, None]
    img = img if img.ndim == 3 else img[..., None]
    top = img[y0][:, x0] * (1 - wx) + img[y0][:, x1] * wx
    bot = img[y1][:, x0] * (1 - wx) + img[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    return out.astype(np.float32)


class Pipeline(FeatureTransformer):
    """Chain a list of transformers (reference: FeatureTransformer
    Pipeline, pyspark image.py:51)."""

    def __init__(self, transformers: List[FeatureTransformer]):
        self.transformers = list(transformers)

    def transform(self, feature):
        for t in self.transformers:
            feature = t(feature)
        return feature


class PixelNormalize(PixelNormalizer):
    """pyspark spelling of PixelNormalizer; accepts the means as a flat
    H*W*C array and reshapes against the incoming image
    (reference: pyspark image.py:390 PixelNormalize)."""

    def transform(self, feature):
        img = feature["image"]
        feature["image"] = img - self.means.reshape(img.shape)
        return feature


class FixedCrop(FeatureTransformer):
    """Crop a fixed area; coordinates normalized to [0,1] or absolute
    (reference: pyspark FixedCrop :426)."""

    def __init__(self, x1, y1, x2, y2, normalized=True, is_clip=True):
        self.box = (x1, y1, x2, y2)
        self.normalized = normalized
        self.is_clip = is_clip

    def _crop(self, img, box):
        h, w = img.shape[:2]
        x1, y1, x2, y2 = box
        if self.normalized:
            x1, y1, x2, y2 = x1 * w, y1 * h, x2 * w, y2 * h
        if self.is_clip:
            x1, x2 = max(0.0, x1), min(float(w), x2)
            y1, y2 = max(0.0, y1), min(float(h), y2)
        x1, y1, x2, y2 = (int(round(v)) for v in (x1, y1, x2, y2))
        return img[y1:y2, x1:x2]

    def transform(self, feature):
        feature["image"] = self._crop(feature["image"], self.box)
        return feature


class DetectionCrop(FixedCrop):
    """Crop to the detection stored under ``roi_key`` (first box, layout
    [..., x1, y1, x2, y2] tail -- reference: DetectionCrop.scala)."""

    def __init__(self, roi_key, normalized=True):
        super().__init__(0, 0, 1, 1, normalized=normalized, is_clip=True)
        self.roi_key = roi_key

    def transform(self, feature):
        roi = np.asarray(feature[self.roi_key], np.float32).reshape(-1)
        feature["image"] = self._crop(feature["image"], tuple(roi[-4:]))
        return feature


class MatToFloats(FeatureTransformer):
    """Expose the decoded image as a flat float array under ``out_key``
    (reference: pyspark MatToFloats :583; the mat-release/share-buffer
    mechanics are OpenCV memory management with no analogue here)."""

    def __init__(self, valid_height=300, valid_width=300, valid_channel=3,
                 out_key="floats", share_buffer=True):
        self.valid = (valid_height, valid_width, valid_channel)
        self.out_key = out_key

    def transform(self, feature):
        img = feature.get("image")
        if img is None:                      # invalid image: typed zeros
            img = np.zeros(self.valid, np.float32)
        feature[self.out_key] = np.asarray(img, np.float32)
        return feature


class MatToTensor(FeatureTransformer):
    """Store the image as a CHW float tensor under ``tensor_key``
    (reference: pyspark MatToTensor :598 -- the JVM tensor is CHW).
    ``to_rgb`` flips the channel order (the reference's mats are BGR;
    images decoded here are already RGB, so this flips only when the
    pipeline upstream produced reversed channels)."""

    def __init__(self, to_rgb=False, tensor_key="imageTensor"):
        self.to_rgb = to_rgb
        self.tensor_key = tensor_key

    def transform(self, feature):
        img = np.asarray(feature["image"], np.float32)
        if self.to_rgb:
            img = img[..., ::-1]
        feature[self.tensor_key] = np.transpose(img, (2, 0, 1)).copy()
        return feature


class ImageFrameToSample(FeatureTransformer):
    """Build the Sample from stored tensors (reference: pyspark
    ImageFrameToSample :651)."""

    def __init__(self, input_keys=("imageTensor",), target_keys=None,
                 sample_key="sample"):
        self.input_keys = list(input_keys)
        self.target_keys = list(target_keys) if target_keys else None
        self.sample_key = sample_key

    def transform(self, feature):
        ins = [np.asarray(feature[k], np.float32) for k in self.input_keys]
        tgts = None
        if self.target_keys:
            tgts = [np.asarray(feature[k], np.float32)
                    for k in self.target_keys]
            tgts = tgts[0] if len(tgts) == 1 else tgts
        feature[self.sample_key] = Sample(
            ins[0] if len(ins) == 1 else ins, tgts)
        return feature


class BytesToMat(FeatureTransformer):
    """Decode an original image file's bytes into the image array
    (reference: pyspark BytesToMat :644)."""

    def __init__(self, byte_key="bytes"):
        self.byte_key = byte_key

    def transform(self, feature):
        import io

        from PIL import Image

        img = Image.open(io.BytesIO(feature[self.byte_key])).convert("RGB")
        feature["image"] = np.asarray(img, np.float32)
        feature["original_size"] = feature["image"].shape
        return feature


class PixelBytesToMat(FeatureTransformer):
    """Raw HWC pixel bytes -> image array; the pixel buffer carries no
    shape, so the feature must hold ``original_size``
    (reference: pyspark PixelBytesToMat :657)."""

    def __init__(self, byte_key="bytes"):
        self.byte_key = byte_key

    def transform(self, feature):
        shape = tuple(feature["original_size"])
        buf = np.frombuffer(feature[self.byte_key], np.uint8)
        feature["image"] = buf.reshape(shape).astype(np.float32)
        return feature


class FixExpand(FeatureTransformer):
    """Expand to (expand_height, expand_width), original image centered,
    blank filled with zeros (reference: pyspark FixExpand :664)."""

    def __init__(self, expand_height, expand_width):
        self.eh, self.ew = int(expand_height), int(expand_width)

    def transform(self, feature):
        img = feature["image"]
        h, w = img.shape[:2]
        out = np.zeros((self.eh, self.ew) + img.shape[2:], img.dtype)
        y0, x0 = (self.eh - h) // 2, (self.ew - w) // 2
        out[y0:y0 + h, x0:x0 + w] = img
        feature["image"] = out
        return feature


class RandomAspectScale(FeatureTransformer):
    """Aspect-preserving resize with the short-side target drawn from
    ``scales`` (reference: pyspark RandomAspectScale :633)."""

    def __init__(self, scales, scale_multiple_of=1, max_size=1000,
                 seed: Optional[int] = None):
        self.scales = list(scales)
        self.multiple_of = int(scale_multiple_of)
        self.max_size = int(max_size)
        self.rng = np.random.default_rng(seed)

    def transform(self, feature):
        img = feature["image"]
        h, w = img.shape[:2]
        scale = self.scales[int(self.rng.integers(0, len(self.scales)))]
        ratio = scale / min(h, w)
        if max(h, w) * ratio > self.max_size:
            ratio = self.max_size / max(h, w)
        nh, nw = int(round(h * ratio)), int(round(w * ratio))
        if self.multiple_of > 1:
            nh -= nh % self.multiple_of
            nw -= nw % self.multiple_of
        feature["image"] = bilinear_resize(img, max(nh, 1), max(nw, 1))
        return feature


class RandomAlterAspect(FeatureTransformer):
    """Random area-ratio crop with aspect jitter, resized to a square of
    ``crop_length`` (reference: pyspark RandomAlterAspect :685 -- the
    caffe PCA-style aspect augmentation)."""

    def __init__(self, min_area_ratio, max_area_ratio,
                 min_aspect_ratio_change, interp_mode="CUBIC",
                 crop_length=224, seed: Optional[int] = None):
        self.min_area = float(min_area_ratio)
        self.max_area = float(max_area_ratio)
        self.aspect_change = float(min_aspect_ratio_change)
        self.crop_length = int(crop_length)
        self.rng = np.random.default_rng(seed)

    def transform(self, feature):
        img = feature["image"]
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * self.rng.uniform(self.min_area, self.max_area)
            aspect = self.rng.uniform(self.aspect_change,
                                      1.0 / max(self.aspect_change, 1e-6))
            ch = int(round(np.sqrt(target / aspect)))
            cw = int(round(np.sqrt(target * aspect)))
            if ch <= h and cw <= w and ch > 0 and cw > 0:
                y0 = int(self.rng.integers(0, h - ch + 1))
                x0 = int(self.rng.integers(0, w - cw + 1))
                img = img[y0:y0 + ch, x0:x0 + cw]
                break
        feature["image"] = bilinear_resize(img, self.crop_length,
                                           self.crop_length)
        return feature


class RandomCropper(FeatureTransformer):
    """Fixed-size crop (random or center) with random mirror
    (reference: pyspark RandomCropper :705; cropper_method "Random" or
    "Center")."""

    def __init__(self, crop_w, crop_h, mirror=True, cropper_method="Random",
                 channels=3, seed: Optional[int] = None):
        self.w, self.h = int(crop_w), int(crop_h)
        self.mirror = mirror
        self.method = cropper_method
        self.rng = np.random.default_rng(seed)

    def transform(self, feature):
        img = feature["image"]
        h, w = img.shape[:2]
        if str(self.method).lower() == "random":
            y0 = int(self.rng.integers(0, max(h - self.h, 0) + 1))
            x0 = int(self.rng.integers(0, max(w - self.w, 0) + 1))
        else:
            y0, x0 = (h - self.h) // 2, (w - self.w) // 2
        img = img[y0:y0 + self.h, x0:x0 + self.w]
        if self.mirror and self.rng.uniform() < 0.5:
            img = img[:, ::-1]
        feature["image"] = np.ascontiguousarray(img)
        return feature


class LocalImageFrame(ImageFrame):
    """Explicitly host-local frame (reference: ImageFrame.scala
    LocalImageFrame); ImageFrame already is local here."""


class DistributedImageFrame:
    """ImageFrame over a partitioned source of ImageFeatures (reference:
    DistributedImageFrame over an RDD).  Transforms apply lazily per
    partition through the same PartitionedSource protocol the training
    ingest uses (dataset/distributed.py)."""

    def __init__(self, source, transformers=None):
        self.source = source
        self.transformers = list(transformers or [])

    def transform(self, transformer) -> "DistributedImageFrame":
        self.transformers.append(transformer)
        return self

    __rshift__ = transform

    def num_partitions(self):
        return self.source.num_partitions()

    def partition(self, idx) -> List[ImageFeature]:
        feats = list(self.source.partition(idx))
        for t in self.transformers:
            feats = [t(f) for f in feats]
        return feats

    def to_samples(self) -> List[Sample]:
        out = []
        to_sample = MatToSample()
        for i in range(self.num_partitions()):
            for f in self.partition(i):
                if "sample" not in f:
                    f = to_sample(f)
                out.append(f["sample"])
        return out


class _SeqFilePartitions:
    """Lazy PartitionedSource: one partition per .seq file, decoded on
    demand -- ImageNet-scale folders must not materialise in memory."""

    def __init__(self, files, class_num, resize):
        self.files, self.class_num, self.resize = files, class_num, resize

    def num_partitions(self):
        return len(self.files)

    def count(self):
        return sum(1 for i in range(len(self.files))
                   for _ in self.partition(i))

    def partition(self, idx):
        import io

        from PIL import Image

        from bigdl_tpu.dataset.seq_file import read_byte_records

        for data, label in read_byte_records(self.files[idx],
                                             class_num=self.class_num):
            img = Image.open(io.BytesIO(data)).convert("RGB")
            if self.resize:
                img = img.resize((self.resize, self.resize))
            yield ImageFeature(np.asarray(img, np.float32),
                               label=int(float(label)) - 1)


class SeqFileFolder:
    """Hadoop SequenceFile folder -> DistributedImageFrame (reference:
    pyspark SeqFileFolder.files_to_image_frame :726, the ImageNet
    ingest).  One lazy partition per .seq file: memory stays bounded by
    a partition, like the reference's RDD."""

    @classmethod
    def files_to_image_frame(cls, url, sc=None, class_num=1000,
                             partition_num=-1, resize=None):
        from bigdl_tpu.dataset.seq_file import find_seq_files

        return DistributedImageFrame(
            _SeqFilePartitions(find_seq_files(url), class_num, resize))
