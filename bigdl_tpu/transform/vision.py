"""Vision pipeline: ImageFeature/ImageFrame + composable augmentations.

Reference: transform/vision/image/ImageFeature.scala:36 (hash-map of stages),
ImageFrame.scala (local/distributed containers), FeatureTransformer.scala
(composable augs), augmentation/ (18 transforms: Resize, CenterCrop,
RandomCrop, HFlip, ChannelNormalize, Brightness, Contrast, Saturation,
PixelNormalizer, RandomTransformer, ...).

Host-side (CPU) numpy implementations -- TPUs don't decode images
(SURVEY.md section 2.8: keep the image pipeline pure host-side).  Layout
HWC float32; the pipeline ends in Samples feeding SampleToMiniBatch.
"""

from typing import Callable, Dict, List, Optional

import numpy as np

from bigdl_tpu.dataset.minibatch import Sample


class ImageFeature(dict):
    """Mutable per-image state dict (reference: ImageFeature.scala:36).

    Well-known keys: 'image' (HWC float32), 'label', 'path',
    'original_size'.
    """

    def __init__(self, image=None, label=None, path=None):
        super().__init__()
        if image is not None:
            self["image"] = np.asarray(image, np.float32)
            self["original_size"] = self["image"].shape
        if label is not None:
            self["label"] = label
        if path is not None:
            self["path"] = path

    @property
    def image(self):
        return self["image"]

    @image.setter
    def image(self, v):
        self["image"] = v


class FeatureTransformer:
    """Composable ImageFeature -> ImageFeature stage
    (reference: FeatureTransformer.scala; compose with ``>>``)."""

    def transform(self, feature: ImageFeature) -> ImageFeature:
        raise NotImplementedError

    def __call__(self, feature):
        return self.transform(feature)

    def __rshift__(self, other):
        return _Chained(self, other)


class _Chained(FeatureTransformer):
    def __init__(self, a, b):
        self.a, self.b = a, b

    def transform(self, feature):
        return self.b(self.a(feature))


class Resize(FeatureTransformer):
    """Bilinear resize (reference: augmentation/Resize.scala)."""

    def __init__(self, height: int, width: int):
        self.h, self.w = height, width

    def transform(self, feature):
        feature["image"] = bilinear_resize(feature["image"], self.h, self.w)
        return feature


class AspectScale(FeatureTransformer):
    """Scale the short side to ``scale`` keeping aspect
    (reference: augmentation/AspectScale.scala)."""

    def __init__(self, scale: int, max_size: int = 1000):
        self.scale, self.max_size = scale, max_size

    def transform(self, feature):
        img = feature["image"]
        h, w = img.shape[:2]
        ratio = self.scale / min(h, w)
        if max(h, w) * ratio > self.max_size:
            ratio = self.max_size / max(h, w)
        feature["image"] = bilinear_resize(
            img, int(round(h * ratio)), int(round(w * ratio)))
        return feature


class CenterCrop(FeatureTransformer):
    def __init__(self, height: int, width: int):
        self.h, self.w = height, width

    def transform(self, feature):
        img = feature["image"]
        h, w = img.shape[:2]
        y0, x0 = (h - self.h) // 2, (w - self.w) // 2
        feature["image"] = img[y0:y0 + self.h, x0:x0 + self.w]
        return feature


class RandomCrop(FeatureTransformer):
    def __init__(self, height: int, width: int, seed: Optional[int] = None):
        self.h, self.w = height, width
        self.rng = np.random.default_rng(seed)

    def transform(self, feature):
        img = feature["image"]
        h, w = img.shape[:2]
        y0 = int(self.rng.integers(0, h - self.h + 1))
        x0 = int(self.rng.integers(0, w - self.w + 1))
        feature["image"] = img[y0:y0 + self.h, x0:x0 + self.w]
        return feature


class HFlip(FeatureTransformer):
    """Horizontal flip (reference: augmentation/HFlip.scala)."""

    def transform(self, feature):
        feature["image"] = feature["image"][:, ::-1]
        return feature


class RandomHFlip(FeatureTransformer):
    def __init__(self, prob=0.5, seed: Optional[int] = None):
        self.prob = prob
        self.rng = np.random.default_rng(seed)

    def transform(self, feature):
        if self.rng.random() < self.prob:
            feature["image"] = feature["image"][:, ::-1]
        return feature


class ChannelNormalize(FeatureTransformer):
    """(x - mean) / std per channel (reference: augmentation/ChannelNormalize.scala)."""

    def __init__(self, mean, std):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def transform(self, feature):
        feature["image"] = (feature["image"] - self.mean) / self.std
        return feature


class PixelNormalizer(FeatureTransformer):
    """Subtract a full mean image (reference: augmentation/PixelNormalizer.scala)."""

    def __init__(self, means: np.ndarray):
        self.means = np.asarray(means, np.float32)

    def transform(self, feature):
        feature["image"] = feature["image"] - self.means
        return feature


class Brightness(FeatureTransformer):
    def __init__(self, delta_low, delta_high, seed: Optional[int] = None):
        self.low, self.high = delta_low, delta_high
        self.rng = np.random.default_rng(seed)

    def transform(self, feature):
        feature["image"] = feature["image"] + self.rng.uniform(self.low,
                                                               self.high)
        return feature


class Contrast(FeatureTransformer):
    def __init__(self, delta_low, delta_high, seed: Optional[int] = None):
        self.low, self.high = delta_low, delta_high
        self.rng = np.random.default_rng(seed)

    def transform(self, feature):
        feature["image"] = feature["image"] * self.rng.uniform(self.low,
                                                               self.high)
        return feature


class Saturation(FeatureTransformer):
    """Blend with the grayscale image (reference: augmentation/Saturation.scala)."""

    def __init__(self, delta_low, delta_high, seed: Optional[int] = None):
        self.low, self.high = delta_low, delta_high
        self.rng = np.random.default_rng(seed)

    def transform(self, feature):
        img = feature["image"]
        gray = img.mean(axis=-1, keepdims=True)
        alpha = self.rng.uniform(self.low, self.high)
        feature["image"] = gray + alpha * (img - gray)
        return feature


class ChannelScaledNormalizer(FeatureTransformer):
    def __init__(self, scale: float):
        self.scale = scale

    def transform(self, feature):
        feature["image"] = feature["image"] * self.scale
        return feature


class RandomTransformer(FeatureTransformer):
    """Apply inner transformer with probability ``prob``
    (reference: augmentation/RandomTransformer.scala)."""

    def __init__(self, inner: FeatureTransformer, prob: float,
                 seed: Optional[int] = None):
        self.inner = inner
        self.prob = prob
        self.rng = np.random.default_rng(seed)

    def transform(self, feature):
        if self.rng.random() < self.prob:
            return self.inner(feature)
        return feature


class Expand(FeatureTransformer):
    """Place the image on a mean-filled larger canvas at a random offset,
    recording the inverse boundary box for RoiProject (reference:
    augmentation/Expand.scala -- SSD zoom-out augmentation)."""

    def __init__(self, means_r=123, means_g=117, means_b=104,
                 min_expand_ratio=1.0, max_expand_ratio=4.0,
                 seed: Optional[int] = None):
        self.means = np.asarray([means_r, means_g, means_b], np.float32)
        self.min_ratio, self.max_ratio = min_expand_ratio, max_expand_ratio
        self.rng = np.random.default_rng(seed)

    def transform(self, feature):
        if abs(self.max_ratio - 1.0) < 1e-2:
            return feature
        img = feature["image"]
        h, w = img.shape[:2]
        ratio = self.rng.uniform(self.min_ratio, self.max_ratio)
        nh, nw = int(h * ratio), int(w * ratio)
        h_off = float(np.floor(self.rng.uniform(0, nh - h)))
        w_off = float(np.floor(self.rng.uniform(0, nw - w)))
        canvas = np.tile(self.means, (nh, nw, 1)).astype(np.float32)
        canvas[int(h_off):int(h_off) + h, int(w_off):int(w_off) + w] = img
        feature["image"] = canvas
        if "label" in feature:
            from bigdl_tpu.transform.vision_roi import BoundingBox

            feature["bounding_box"] = BoundingBox(
                -w_off / w, -h_off / h, (nw - w_off) / w, (nh - h_off) / h)
        return feature


class Filler(FeatureTransformer):
    """Fill a normalized sub-rectangle with a constant (reference:
    augmentation/Filler.scala)."""

    def __init__(self, start_x, start_y, end_x, end_y, value=255):
        self.box = (start_x, start_y, end_x, end_y)
        self.value = value

    def transform(self, feature):
        img = feature["image"]
        h, w = img.shape[:2]
        x1, y1, x2, y2 = self.box
        img[int(y1 * h):int(y2 * h), int(x1 * w):int(x2 * w)] = self.value
        feature["image"] = img
        return feature


def _rgb_to_hsv(img):
    import colorsys  # noqa: F401 (documenting the convention)
    r, g, b = img[..., 0], img[..., 1], img[..., 2]
    maxc = img.max(-1)
    minc = img.min(-1)
    v = maxc
    span = np.where(maxc > 0, maxc - minc, 1.0)
    s = np.where(maxc > 0, (maxc - minc) / np.where(maxc == 0, 1, maxc), 0)
    rc = (maxc - r) / span
    gc = (maxc - g) / span
    bc = (maxc - b) / span
    h = np.where(maxc == minc, 0.0,
                 np.where(maxc == r, bc - gc,
                          np.where(maxc == g, 2.0 + rc - bc,
                                   4.0 + gc - rc)))
    h = (h / 6.0) % 1.0
    return h, s, v


def _hsv_to_rgb(h, s, v):
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1.0 - s)
    q = v * (1.0 - s * f)
    t = v * (1.0 - s * (1.0 - f))
    i = i.astype(np.int32) % 6
    conds = [i == k for k in range(6)]
    r = np.select(conds, [v, q, p, p, t, v])
    g = np.select(conds, [t, v, v, q, p, p])
    b = np.select(conds, [p, p, t, v, v, q])
    return np.stack([r, g, b], -1)


class Hue(FeatureTransformer):
    """Rotate the hue channel by a random angle in degrees (reference:
    augmentation/Hue.scala -- HSV-space hue shift)."""

    def __init__(self, delta_low=-18.0, delta_high=18.0,
                 seed: Optional[int] = None):
        self.low, self.high = delta_low, delta_high
        self.rng = np.random.default_rng(seed)

    def transform(self, feature):
        img = np.clip(feature["image"], 0, 255).astype(np.float32)
        scale = 255.0 if img.max() > 1.5 else 1.0
        h, s, v = _rgb_to_hsv(img / scale)
        delta = self.rng.uniform(self.low, self.high) / 360.0
        h = (h + delta) % 1.0
        feature["image"] = (_hsv_to_rgb(h, s, v) * scale).astype(np.float32)
        return feature


class ChannelOrder(FeatureTransformer):
    """Randomly permute the color channels (reference:
    augmentation/ChannelOrder.scala)."""

    def __init__(self, seed: Optional[int] = None):
        self.rng = np.random.default_rng(seed)

    def transform(self, feature):
        perm = self.rng.permutation(3)
        feature["image"] = np.ascontiguousarray(feature["image"][..., perm])
        return feature


class ColorJitter(FeatureTransformer):
    """Brightness/contrast/saturation/hue in random order (reference:
    augmentation/ColorJitter.scala)."""

    def __init__(self, brightness_prob=0.5, brightness_delta=32,
                 contrast_prob=0.5, contrast_lower=0.5, contrast_upper=1.5,
                 saturation_prob=0.5, saturation_lower=0.5,
                 saturation_upper=1.5, hue_prob=0.5, hue_delta=18,
                 seed: Optional[int] = None):
        rng = np.random.default_rng(seed)
        self.rng = rng
        self.stages = [
            RandomTransformer(
                Brightness(-brightness_delta, brightness_delta,
                           seed=int(rng.integers(1 << 31))),
                brightness_prob, seed=int(rng.integers(1 << 31))),
            RandomTransformer(
                Contrast(contrast_lower, contrast_upper,
                         seed=int(rng.integers(1 << 31))),
                contrast_prob, seed=int(rng.integers(1 << 31))),
            RandomTransformer(
                Saturation(saturation_lower, saturation_upper,
                           seed=int(rng.integers(1 << 31))),
                saturation_prob, seed=int(rng.integers(1 << 31))),
            RandomTransformer(
                Hue(-hue_delta, hue_delta, seed=int(rng.integers(1 << 31))),
                hue_prob, seed=int(rng.integers(1 << 31))),
        ]

    def transform(self, feature):
        for i in self.rng.permutation(len(self.stages)):
            feature = self.stages[i](feature)
        return feature


class RandomResize(FeatureTransformer):
    """Resize to a random scale from a list (reference:
    augmentation/RandomResize.scala)."""

    def __init__(self, min_size, max_size, seed: Optional[int] = None):
        self.min_size, self.max_size = min_size, max_size
        self.rng = np.random.default_rng(seed)

    def transform(self, feature):
        size = int(self.rng.integers(self.min_size, self.max_size + 1))
        return Resize(size, size)(feature)


class MTImageFeatureToBatch:
    """Parallel decode/augment/batch assembler (reference:
    MTImageFeatureToBatch.scala: multi-threaded ImageFeature -> MiniBatch
    with fixed output size; detection labels batch as a list of RoiLabels
    since box counts vary per image)."""

    def __init__(self, width, height, batch_size,
                 transformer: Optional[FeatureTransformer] = None,
                 to_rgb=False, extract_roi=False, num_threads=4):
        import threading

        self.width, self.height = width, height
        self.batch_size = batch_size
        self.transformer = transformer
        self.extract_roi = extract_roi
        self.num_threads = num_threads
        # np.random.Generator (inside the random augmentations) is not
        # thread-safe; the reference clones the transformer per thread
        # (MTImageFeatureToBatch.scala), here a lock serialises the cheap
        # augment stage while decode/resize stay parallel
        self._transform_lock = threading.Lock()

    def _one(self, feature):
        if self.transformer is not None:
            with self._transform_lock:
                feature = self.transformer(feature)
        img = feature["image"]
        if img.shape[:2] != (self.height, self.width):
            img = bilinear_resize(img, self.height, self.width)
        return img, feature.get("label")

    def __call__(self, features):
        """iterable of ImageFeature -> yields (images (B,H,W,3), labels)."""
        from concurrent.futures import ThreadPoolExecutor

        batch = []
        with ThreadPoolExecutor(self.num_threads) as pool:
            for img, label in pool.map(self._one, features):
                batch.append((img, label))
                if len(batch) == self.batch_size:
                    yield self._assemble(batch)
                    batch = []
        if batch:
            yield self._assemble(batch)

    def _assemble(self, batch):
        images = np.stack([b[0] for b in batch]).astype(np.float32)
        labels = [b[1] for b in batch]
        if self.extract_roi:
            return images, labels        # list of RoiLabel
        if all(l is not None and np.ndim(l) == 0 for l in labels):
            return images, np.asarray(labels)
        return images, labels


class MatToSample(FeatureTransformer):
    """Terminal stage: ImageFeature -> Sample
    (reference: ImageFrameToSample / MatToTensor)."""

    def transform(self, feature):
        feature["sample"] = Sample(feature["image"], feature.get("label"))
        return feature


class ImageFrame:
    """Local collection of ImageFeatures (reference: ImageFrame.scala
    LocalImageFrame; the distributed variant shards like
    DistributedDataSet)."""

    def __init__(self, features: List[ImageFeature]):
        self.features = features

    @staticmethod
    def from_arrays(images, labels=None):
        labels = labels if labels is not None else [None] * len(images)
        return ImageFrame([ImageFeature(im, lb)
                           for im, lb in zip(images, labels)])

    def transform(self, transformer: FeatureTransformer) -> "ImageFrame":
        self.features = [transformer(f) for f in self.features]
        return self

    def __rshift__(self, transformer):
        return self.transform(transformer)

    def to_samples(self) -> List[Sample]:
        self.transform(MatToSample())
        return [f["sample"] for f in self.features]


def bilinear_resize(img: np.ndarray, out_h: int, out_w: int) -> np.ndarray:
    """Pure-numpy bilinear resize, align_corners=False (OpenCV-compatible
    sampling grid)."""
    h, w = img.shape[:2]
    if (h, w) == (out_h, out_w):
        return img
    ys = (np.arange(out_h) + 0.5) * h / out_h - 0.5
    xs = (np.arange(out_w) + 0.5) * w / out_w - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0.0, 1.0)[:, None, None]
    wx = np.clip(xs - x0, 0.0, 1.0)[None, :, None]
    img = img if img.ndim == 3 else img[..., None]
    top = img[y0][:, x0] * (1 - wx) + img[y0][:, x1] * wx
    bot = img[y1][:, x0] * (1 - wx) + img[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    return out.astype(np.float32)
