"""ROI (detection) label transforms + SSD-style crop sampling.

Reference: transform/vision/image/label/roi/ -- RoiLabel.scala (label
container), RoiTransformer.scala (RoiNormalize/RoiHFlip/RoiResize/
RoiProject), BatchSampler.scala + RandomSampler.scala (SSD batch-sampled
crops), and util/BoundingBox.scala.  Host-side numpy throughout (the TPU
never sees undecoded label plumbing).

Boxes are (N, 4) float32 ``[x1, y1, x2, y2]`` arrays; ``classes`` is
(N,) or (2, N) (the reference stores difficult-flags in a second row).
"""

from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

from bigdl_tpu.transform.vision import FeatureTransformer, ImageFeature


@dataclass
class RoiLabel:
    """Detection label (reference: label/roi/RoiLabel.scala)."""

    classes: np.ndarray            # (N,) or (2, N) float32
    bboxes: np.ndarray             # (N, 4) float32 x1,y1,x2,y2

    def size(self) -> int:
        return int(self.bboxes.shape[0])

    def copy(self) -> "RoiLabel":
        return RoiLabel(np.array(self.classes), np.array(self.bboxes))


@dataclass
class BoundingBox:
    """reference: transform/vision/image/util/BoundingBox.scala."""

    x1: float = 0.0
    y1: float = 0.0
    x2: float = 1.0
    y2: float = 1.0
    normalized: bool = True

    def width(self):
        return self.x2 - self.x1

    def height(self):
        return self.y2 - self.y1

    def area(self):
        return max(self.width(), 0.0) * max(self.height(), 0.0)

    def jaccard_overlap(self, other: "BoundingBox") -> float:
        ix1, iy1 = max(self.x1, other.x1), max(self.y1, other.y1)
        ix2, iy2 = min(self.x2, other.x2), min(self.y2, other.y2)
        iw, ih = max(ix2 - ix1, 0.0), max(iy2 - iy1, 0.0)
        inter = iw * ih
        union = self.area() + other.area() - inter
        return inter / union if union > 0 else 0.0

    def locate(self, box: "BoundingBox") -> "BoundingBox":
        """Map a [0,1]-space box into this box's coordinate frame
        (reference: BoundingBox.locateBBox)."""
        w, h = self.width(), self.height()
        return BoundingBox(self.x1 + box.x1 * w, self.y1 + box.y1 * h,
                           self.x1 + box.x2 * w, self.y1 + box.y2 * h)

    def contains_center(self, bbox_row) -> bool:
        cx = (bbox_row[0] + bbox_row[2]) / 2
        cy = (bbox_row[1] + bbox_row[3]) / 2
        return self.x1 <= cx <= self.x2 and self.y1 <= cy <= self.y2


def scale_bboxes(bboxes: np.ndarray, scale_h: float, scale_w: float):
    """In-place scale (reference: BboxUtil.scaleBBox -- x by width scale,
    y by height scale)."""
    bboxes[:, 0] *= scale_w
    bboxes[:, 2] *= scale_w
    bboxes[:, 1] *= scale_h
    bboxes[:, 3] *= scale_h


class RoiNormalize(FeatureTransformer):
    """Scale boxes to [0, 1] (reference: RoiTransformer.scala RoiNormalize)."""

    def transform(self, feature: ImageFeature) -> ImageFeature:
        h, w = feature["image"].shape[:2]
        label: RoiLabel = feature["label"]
        scale_bboxes(label.bboxes, 1.0 / h, 1.0 / w)
        return feature


class RoiHFlip(FeatureTransformer):
    """Mirror boxes horizontally (reference: RoiHFlip)."""

    def __init__(self, normalized: bool = True):
        self.normalized = normalized

    def transform(self, feature: ImageFeature) -> ImageFeature:
        label: RoiLabel = feature["label"]
        width = 1.0 if self.normalized else feature["image"].shape[1]
        x1 = width - label.bboxes[:, 0].copy()
        label.bboxes[:, 0] = width - label.bboxes[:, 2]
        label.bboxes[:, 2] = x1
        return feature


class RoiResize(FeatureTransformer):
    """Scale un-normalized boxes by the resize factor (reference: RoiResize)."""

    def __init__(self, normalized: bool = False):
        self.normalized = normalized

    def transform(self, feature: ImageFeature) -> ImageFeature:
        if not self.normalized:
            orig = feature.get("original_size", feature["image"].shape)
            oh, ow = orig[0], orig[1]
            h, w = feature["image"].shape[:2]
            scale_bboxes(feature["label"].bboxes, h / oh, w / ow)
        return feature


class RoiProject(FeatureTransformer):
    """Project normalized gt boxes onto the image-boundary box stored at
    feature["bounding_box"], dropping boxes that leave the crop (reference:
    RoiProject: clip to the boundary, optionally require the box center
    inside, then re-express in the boundary's frame)."""

    def __init__(self, need_meet_center_constraint: bool = True):
        self.need_center = need_meet_center_constraint

    def transform(self, feature: ImageFeature) -> ImageFeature:
        boundary: BoundingBox = feature["bounding_box"]
        label: RoiLabel = feature["label"]
        keep, new_boxes = [], []
        bw, bh = boundary.width(), boundary.height()
        for i in range(label.size()):
            row = label.bboxes[i]
            if self.need_center and not boundary.contains_center(row):
                continue
            x1 = max(row[0], boundary.x1)
            y1 = max(row[1], boundary.y1)
            x2 = min(row[2], boundary.x2)
            y2 = min(row[3], boundary.y2)
            if x2 <= x1 or y2 <= y1:
                continue
            keep.append(i)
            new_boxes.append([(x1 - boundary.x1) / bw,
                              (y1 - boundary.y1) / bh,
                              (x2 - boundary.x1) / bw,
                              (y2 - boundary.y1) / bh])
        classes = (label.classes[..., keep] if label.classes.ndim > 1
                   else label.classes[keep])
        feature["label"] = RoiLabel(
            np.asarray(classes, np.float32),
            np.asarray(new_boxes, np.float32).reshape(-1, 4))
        return feature


class BatchSampler:
    """Sample crop boxes satisfying scale/aspect/overlap constraints
    (reference: label/roi/BatchSampler.scala)."""

    def __init__(self, max_sample=1, max_trials=50, min_scale=1.0,
                 max_scale=1.0, min_aspect_ratio=1.0, max_aspect_ratio=1.0,
                 min_overlap: Optional[float] = None,
                 max_overlap: Optional[float] = None):
        assert 0 < min_scale <= max_scale <= 1
        assert 0 < min_aspect_ratio <= 1 <= max_aspect_ratio
        self.max_sample = max_sample
        self.max_trials = max_trials
        self.min_scale, self.max_scale = min_scale, max_scale
        self.min_ar, self.max_ar = min_aspect_ratio, max_aspect_ratio
        self.min_overlap, self.max_overlap = min_overlap, max_overlap

    def _sample_box(self, rng) -> BoundingBox:
        scale = rng.uniform(self.min_scale, self.max_scale)
        ratio = rng.uniform(self.min_ar, self.max_ar)
        ratio = min(max(ratio, scale * scale), 1.0 / scale / scale)
        w, h = scale * np.sqrt(ratio), scale / np.sqrt(ratio)
        x1 = rng.uniform(0, 1 - w)
        y1 = rng.uniform(0, 1 - h)
        return BoundingBox(x1, y1, x1 + w, y1 + h)

    def _satisfies(self, box: BoundingBox, label: RoiLabel) -> bool:
        if self.min_overlap is None and self.max_overlap is None:
            return True
        for i in range(label.size()):
            r = label.bboxes[i]
            o = box.jaccard_overlap(BoundingBox(r[0], r[1], r[2], r[3]))
            if (self.min_overlap is None or o >= self.min_overlap) and \
                    (self.max_overlap is None or o <= self.max_overlap):
                return True
        return False

    def sample(self, source: BoundingBox, label: RoiLabel,
               out: List[BoundingBox], rng):
        found = 0
        for _ in range(self.max_trials):
            if found >= self.max_sample:
                return
            box = source.locate(self._sample_box(rng))
            if self._satisfies(box, label):
                found += 1
                out.append(box)


#: the SSD training sampler set (reference: RandomSampler usage in the
#: pipeline configs: full image + jaccard thresholds .1/.3/.5/.7/.9 + max)
SSD_SAMPLERS = [
    BatchSampler(),
    BatchSampler(min_scale=0.3, min_aspect_ratio=0.5, max_aspect_ratio=2.0,
                 min_overlap=0.1),
    BatchSampler(min_scale=0.3, min_aspect_ratio=0.5, max_aspect_ratio=2.0,
                 min_overlap=0.3),
    BatchSampler(min_scale=0.3, min_aspect_ratio=0.5, max_aspect_ratio=2.0,
                 min_overlap=0.5),
    BatchSampler(min_scale=0.3, min_aspect_ratio=0.5, max_aspect_ratio=2.0,
                 min_overlap=0.7),
    BatchSampler(min_scale=0.3, min_aspect_ratio=0.5, max_aspect_ratio=2.0,
                 min_overlap=0.9),
    BatchSampler(min_scale=0.3, min_aspect_ratio=0.5, max_aspect_ratio=2.0,
                 max_overlap=1.0),
]


class RandomSampler(FeatureTransformer):
    """Pick one sampled crop, crop the image and project the rois
    (reference: label/roi/RandomSampler.scala: sample boxes with all
    samplers, choose one at random, crop + RoiProject)."""

    def __init__(self, samplers: Optional[List[BatchSampler]] = None,
                 seed: int = 0):
        self.samplers = samplers if samplers is not None else SSD_SAMPLERS
        self._rng = np.random.default_rng(seed)
        self._project = RoiProject(True)

    def transform(self, feature: ImageFeature) -> ImageFeature:
        label: RoiLabel = feature["label"]
        unit = BoundingBox(0.0, 0.0, 1.0, 1.0)
        boxes: List[BoundingBox] = []
        for s in self.samplers:
            s.sample(unit, label, boxes, self._rng)
        if not boxes:
            return feature
        pick = boxes[int(self._rng.integers(0, len(boxes)))]
        img = feature["image"]
        h, w = img.shape[:2]
        y1, y2 = int(pick.y1 * h), int(np.ceil(pick.y2 * h))
        x1, x2 = int(pick.x1 * w), int(np.ceil(pick.x2 * w))
        feature["image"] = np.ascontiguousarray(img[y1:y2, x1:x2])
        feature["bounding_box"] = pick
        return self._project.transform(feature)
