"""Text pipeline: tokenization, dictionary, sentence -> Sample.

Reference: dataset/text/ (Dictionary.scala, SentenceTokenizer.scala (OpenNLP),
LabeledSentence.scala, LabeledSentenceToSample.scala, SentenceBiPadding,
TextToLabeledSentence).  OpenNLP is replaced by a regex tokenizer -- same
pipeline contract, no JVM.
"""

import re
from collections import Counter
from typing import Dict, Iterable, List, Optional

import numpy as np

from bigdl_tpu.dataset.minibatch import Sample
from bigdl_tpu.dataset.transformer import Transformer


class SentenceTokenizer(Transformer):
    """Lowercase word tokenizer (reference: SentenceTokenizer.scala)."""

    def __init__(self, pattern=r"[A-Za-z']+|[0-9]+|[^\sA-Za-z0-9]"):
        self.pattern = re.compile(pattern)

    def tokenize(self, sentence: str) -> List[str]:
        return self.pattern.findall(sentence.lower())

    def apply(self, it):
        return (self.tokenize(s) for s in it)


class SentenceBiPadding(Transformer):
    """Wrap sentences in SENTENCESTART/SENTENCEEND markers
    (reference: SentenceBiPadding.scala)."""

    START, END = "SENTENCESTART", "SENTENCEEND"

    def apply(self, it):
        return ([self.START] + list(tokens) + [self.END] for tokens in it)


class Dictionary:
    """Token <-> index vocabulary (reference: Dictionary.scala).

    ``vocab_size`` keeps the most frequent tokens; everything else maps to
    one unknown index (= vocab_size, as in the reference's discard handling).
    """

    def __init__(self, sentences: Optional[Iterable[List[str]]] = None,
                 vocab_size: Optional[int] = None):
        self.word2index: Dict[str, int] = {}
        self.index2word: List[str] = []
        if sentences is not None:
            counts = Counter(t for s in sentences for t in s)
            most = counts.most_common(vocab_size)
            for i, (w, _) in enumerate(most):
                self.word2index[w] = i
                self.index2word.append(w)

    def vocab_size(self) -> int:
        return len(self.index2word)

    def get_index(self, word: str) -> int:
        return self.word2index.get(word, len(self.index2word))

    def get_word(self, index: int) -> str:
        if 0 <= index < len(self.index2word):
            return self.index2word[index]
        return "<unk>"

    def save(self, path: str):
        with open(path, "w") as f:
            for w in self.index2word:
                f.write(w + "\n")

    @staticmethod
    def load(path: str) -> "Dictionary":
        d = Dictionary()
        with open(path) as f:
            for i, line in enumerate(f):
                w = line.rstrip("\n")
                d.word2index[w] = i
                d.index2word.append(w)
        return d


class LabeledSentence:
    """Token-index sequence + target sequence (reference: LabeledSentence.scala)."""

    def __init__(self, data: np.ndarray, label: np.ndarray):
        self.data = np.asarray(data, np.int32)
        self.label = np.asarray(label, np.int32)


class TextToLabeledSentence(Transformer):
    """Next-token LM pairs: data = s[:-1], label = s[1:]
    (reference: TextToLabeledSentence.scala)."""

    def __init__(self, dictionary: Dictionary):
        self.dictionary = dictionary

    def apply(self, it):
        for tokens in it:
            idx = np.asarray([self.dictionary.get_index(t) for t in tokens],
                             np.int32)
            if len(idx) < 2:
                continue
            yield LabeledSentence(idx[:-1], idx[1:])


class LabeledSentenceToSample(Transformer):
    """LabeledSentence -> Sample, padded/truncated to fixed_length
    (reference: LabeledSentenceToSample.scala)."""

    def __init__(self, fixed_length: Optional[int] = None, padding_value=0):
        self.fixed_length = fixed_length
        self.padding_value = padding_value

    def apply(self, it):
        for ls in it:
            data, label = ls.data, ls.label
            if self.fixed_length is not None:
                t = self.fixed_length
                if len(data) >= t:
                    data, label = data[:t], label[:t]
                else:
                    pad = t - len(data)
                    data = np.pad(data, (0, pad),
                                  constant_values=self.padding_value)
                    label = np.pad(label, (0, pad), constant_values=-1)
            yield Sample(data, label)
