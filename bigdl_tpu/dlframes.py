"""DataFrame-style estimator/transformer API.

Reference: dlframes/DLEstimator.scala:163 (Spark ML Estimator whose fit()
returns a DLModel transformer), DLClassifier.scala:37.

Without a JVM/Spark the same contract is exposed sklearn-style: ``fit(X, y)``
returns a fitted ``DLModel`` whose ``transform(X)`` appends predictions.
Accepts numpy arrays or any sequence of rows (the reference supports
Vector/Array/Double feature columns -- here any array-like of fixed shape).
"""

from typing import List, Optional, Sequence

import numpy as np

import bigdl_tpu.nn as nn
from bigdl_tpu.dataset import SampleToMiniBatch, array_dataset
from bigdl_tpu.dataset.minibatch import Sample
from bigdl_tpu.optim.local_optimizer import LocalOptimizer
from bigdl_tpu.optim.optim_method import SGD, OptimMethod
from bigdl_tpu.optim.trigger import Trigger


class DLModel:
    """Fitted transformer (reference: DLModel, dlframes/DLEstimator.scala:362)."""

    def __init__(self, model: nn.Module, feature_size: Sequence[int],
                 batch_size: int = 128):
        self.model = model
        self.feature_size = tuple(feature_size)
        self.batch_size = batch_size

    #: image-frame column consumed by transform() when X is a row list
    #: (reference: DLModel.setFeaturesCol on DLImageTransformer output)
    features_col = "output"

    def set_features_col(self, col):
        """Reference: DLModel.setFeaturesCol."""
        self.features_col = col
        return self

    def transform(self, X) -> np.ndarray:
        """-> predictions, one row per input row.

        Accepts a plain array OR a list of image-schema rows from
        DLImageReader/DLImageTransformer (the reference's
        readImages -> transformer -> model DataFrame flow); rows are
        decoded from ``features_col`` -- a missing column raises rather
        than silently predicting on the wrong one.
        """
        if isinstance(X, list) and X and isinstance(X[0], dict):
            if self.features_col not in X[0]:
                raise KeyError(
                    f"features column {self.features_col!r} not in rows "
                    f"(available: {sorted(X[0])}); use set_features_col()")
            X = np.stack([_row_to_image(r[self.features_col]) for r in X])
        X = np.asarray(X, np.float32).reshape((-1,) + self.feature_size)
        samples = [Sample(x) for x in X]
        return np.stack(self.model.predict(samples, self.batch_size))


class DLClassifierModel(DLModel):
    def transform(self, X) -> np.ndarray:
        """-> class indices (reference: DLClassifierModel argmax semantics)."""
        return np.argmax(super().transform(X), axis=-1)


class DLEstimator:
    """Reference: dlframes/DLEstimator.scala:163."""

    model_cls = DLModel

    def __init__(self, model: nn.Module, criterion,
                 feature_size: Sequence[int] = (),
                 label_size: Sequence[int] = ()):
        self.model = model
        self.criterion = criterion
        #: empty -> inferred from X.shape[1:] at fit() time
        self.feature_size = tuple(feature_size)
        self.label_size = tuple(label_size)
        self.batch_size = 32
        self.max_epoch = 10
        self.optim_method: OptimMethod = SGD(learning_rate=0.01)

    # builder setters mirroring the reference Params
    def set_batch_size(self, n):
        self.batch_size = n
        return self

    def set_max_epoch(self, n):
        self.max_epoch = n
        return self

    def set_learning_rate(self, lr):
        self.optim_method.learning_rate = lr
        return self

    def set_optim_method(self, method: OptimMethod):
        self.optim_method = method
        return self

    def _prepare_labels(self, y):
        return np.asarray(y)

    def fit(self, X, y=None) -> DLModel:
        from bigdl_tpu.dataset.distributed import is_partitioned, source_of

        if is_partitioned(X):
            # a partitioned source / pyspark DataFrame-of-rows (the
            # reference DLEstimator fits on Spark DataFrames,
            # dlframes/DLEstimator.scala): records are (features, label)
            # pairs or objects with .features/.label, converted per
            # cached partition through PartitionedDataSet -- no up-front
            # materialization of the whole source on one host
            if y is not None:
                raise TypeError(
                    "labels ride inside the partitioned rows "
                    "((features, label) pairs or .features/.label "
                    "objects); pass y=None for partitioned sources")
            return self._fit_partitioned(X)
        if y is None:
            raise TypeError("fit(X, y) needs labels unless X is a "
                            "partitioned source of (features, label) rows")
        X = np.asarray(X, np.float32)
        # infer locally -- a later fit() with a new shape must re-infer
        feature_size = self.feature_size or X.shape[1:]
        X = X.reshape((-1,) + feature_size)
        y = self._prepare_labels(y)
        if self.label_size:
            y = y.reshape((-1,) + self.label_size)
        dataset = array_dataset(X, y) >> SampleToMiniBatch(
            self.batch_size, drop_remainder=False)
        opt = LocalOptimizer(self.model, dataset, self.criterion,
                             self.optim_method)
        opt.set_end_when(Trigger.max_epoch(self.max_epoch))
        opt.optimize()
        return self.model_cls(self.model, feature_size, self.batch_size)

    def _fit_partitioned(self, source) -> DLModel:
        from bigdl_tpu.dataset import PartitionedDataSet, Sample
        from bigdl_tpu.dataset.distributed import (PartitionedSource,
                                                   source_of)

        src = source_of(source)
        estimator = self

        def split(r):
            if hasattr(r, "features"):
                return (np.asarray(r.features, np.float32),
                        np.asarray(r.label))
            f, l = r
            return np.asarray(f, np.float32), np.asarray(l)

        first_f, _ = split(next(iter(src.partition(0))))
        feature_size = self.feature_size or first_f.shape

        class _RowPartitions(PartitionedSource):
            def num_partitions(self):
                return src.num_partitions()

            def count(self):
                return src.count()

            def partition(self, idx):
                pairs = [split(r) for r in src.partition(idx)]
                labels = estimator._prepare_labels(
                    np.stack([l for _, l in pairs]))
                if estimator.label_size:
                    labels = labels.reshape((-1,)
                                            + tuple(estimator.label_size))
                return [Sample(f.reshape(feature_size), lab)
                        for (f, _), lab in zip(pairs, labels)]

        dataset = PartitionedDataSet(_RowPartitions()) >> \
            SampleToMiniBatch(self.batch_size, drop_remainder=False)
        opt = LocalOptimizer(self.model, dataset, self.criterion,
                             self.optim_method)
        opt.set_end_when(Trigger.max_epoch(self.max_epoch))
        opt.optimize()
        return self.model_cls(self.model, feature_size, self.batch_size)


class DLClassifier(DLEstimator):
    """Reference: dlframes/DLClassifier.scala:37 -- int labels, argmax out."""

    model_cls = DLClassifierModel

    def __init__(self, model: nn.Module, criterion=None,
                 feature_size: Sequence[int] = ()):
        super().__init__(model, criterion or nn.CrossEntropyCriterion(),
                         feature_size)

    def _prepare_labels(self, y):
        return np.asarray(y, np.int32)


# ---------------------------------------------------------------------------
# Image DataFrames (reference: dlframes/DLImageReader.scala,
# DLImageTransformer.scala; schema DLImageSchema.byteSchema/floatSchema --
# compatible with the Spark 2.3 image format: origin/height/width/nChannels/
# mode/data with row-wise BGR bytes)
# ---------------------------------------------------------------------------

#: OpenCV type codes used in the ``mode`` field (CvType.CV_8UC1 etc.)
CV_8UC1, CV_8UC3, CV_32FC1, CV_32FC3 = 0, 16, 5, 21

IMAGE_SCHEMA = ("origin", "height", "width", "nChannels", "mode", "data")


def _imf_to_row(origin, img_hwc_rgb, float_data):
    """HWC RGB float image -> schema dict (data row-wise BGR like OpenCV)."""
    import numpy as np

    h, w = img_hwc_rgb.shape[:2]
    c = 1 if img_hwc_rgb.ndim == 2 else img_hwc_rgb.shape[2]
    bgr = img_hwc_rgb[..., ::-1] if c == 3 else img_hwc_rgb
    if float_data:
        mode = CV_32FC3 if c == 3 else CV_32FC1
        data = np.ascontiguousarray(bgr, np.float32)
    else:
        mode = CV_8UC3 if c == 3 else CV_8UC1
        data = np.ascontiguousarray(np.clip(bgr, 0, 255), np.uint8).tobytes()
    return {"origin": origin, "height": h, "width": w, "nChannels": c,
            "mode": mode, "data": data}


def _row_to_image(row):
    """schema dict -> HWC RGB float32 array."""
    import numpy as np

    h, w, c = row["height"], row["width"], row["nChannels"]
    if isinstance(row["data"], bytes):
        arr = np.frombuffer(row["data"], np.uint8).astype(np.float32)
    else:
        arr = np.asarray(row["data"], np.float32)
    arr = arr.reshape(h, w, c)
    return arr[..., ::-1] if c == 3 else arr


class DLImageReader:
    """Read an image directory into a list of schema rows, one ``image``
    column per row (reference: DLImageReader.readImages --
    dlframes/DLImageReader.scala; the Spark DataFrame becomes a plain list
    of dict rows in this py-first runtime)."""

    @staticmethod
    def read_images(path) -> list:
        import os

        from bigdl_tpu.dataset.image_folder import _EXTS, decode_image

        paths = []
        for root, _dirs, names in sorted(os.walk(path)):
            for name in sorted(names):
                if name.lower().endswith(_EXTS):
                    paths.append(os.path.join(root, name))
        rows = []
        for p in paths:
            img = decode_image(p) * 255.0   # HWC RGB float32 0..255
            rows.append({"image": _imf_to_row("file://" + str(p), img,
                                              float_data=False)})
        return rows


class DLImageTransformer:
    """Apply a vision FeatureTransformer chain to the image column
    (reference: dlframes/DLImageTransformer.scala: transform -> float
    schema rows ready for DLModel/DLClassifierModel)."""

    def __init__(self, transformer, input_col="image", output_col="output"):
        self.transformer = transformer
        self.input_col = input_col
        self.output_col = output_col

    def transform(self, rows: list) -> list:
        from bigdl_tpu.transform.vision import ImageFeature

        out = []
        for row in rows:
            src = row[self.input_col]
            feat = ImageFeature(_row_to_image(src), path=src.get("origin"))
            feat = self.transformer(feat)
            new = dict(row)
            new[self.output_col] = _imf_to_row(
                src.get("origin"), np.asarray(feat["image"], np.float32),
                float_data=True)
            out.append(new)
        return out
