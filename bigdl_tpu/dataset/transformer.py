"""Transformer chain: composable Iterator -> Iterator stages.

Reference: dataset/Transformer.scala:44 (``Transformer[A, B] =
Iterator[A] => Iterator[B]`` with ``->`` composition) and the
SampleToMiniBatch batcher (:211).
"""

from typing import Iterator, Optional

import numpy as np

from bigdl_tpu.dataset.minibatch import (PaddingParam, Sample,
                                         samples_to_minibatch)


class Transformer:
    """apply(iterator) -> iterator; compose with ``a >> b`` (reference ``->``).

    A stage that maps elements INDEPENDENTLY (no cross-element state, no
    batching) may additionally define ``apply_one(x) -> y``; the async
    input pipeline (``dataset/prefetch.py``) fans such stages out across
    worker threads while order-dependent stages (``SampleToMiniBatch``)
    run serially on the reordered stream.
    """

    def apply(self, it: Iterator) -> Iterator:
        raise NotImplementedError

    def __call__(self, it):
        return self.apply(it)

    def __rshift__(self, other: "Transformer") -> "ChainedTransformer":
        return ChainedTransformer(self, other)


class ChainedTransformer(Transformer):
    def __init__(self, first, second):
        self.first, self.second = first, second

    def apply(self, it):
        return self.second.apply(self.first.apply(it))


class FnTransformer(Transformer):
    """Wrap a per-element function.

    ``parallel_safe`` (default True) declares that ``fn`` is pure per
    element, so ``.prefetch()`` may fan it across worker threads.  Pass
    ``parallel_safe=False`` for a stateful fn -- one drawing from a
    shared seeded RNG (random augmentation), or mutating captured state
    -- which must run single-threaded in source order to keep the
    prefetched batch sequence identical to the synchronous path.
    """

    def __init__(self, fn, parallel_safe: bool = True):
        self.fn = fn
        if not parallel_safe:
            # shadow the class method: prefetch's split_parallel sees no
            # usable apply_one and keeps this stage on the serial path
            self.apply_one = None

    def apply_one(self, x):
        return self.fn(x)

    def apply(self, it):
        return (self.fn(x) for x in it)


class SampleToMiniBatch(Transformer):
    """Group Samples into MiniBatches (reference: Transformer.scala:211).

    Incomplete trailing batches are dropped when ``drop_remainder`` -- the
    distributed path requires static shapes for jit, matching the
    reference's fixed batchSize contract.
    """

    def __init__(self, batch_size: int, feature_padding: Optional[PaddingParam] = None,
                 label_padding: Optional[PaddingParam] = None,
                 drop_remainder: bool = True):
        self.batch_size = batch_size
        self.feature_padding = feature_padding
        self.label_padding = label_padding
        self.drop_remainder = drop_remainder

    def apply(self, it):
        buf = []
        for sample in it:
            buf.append(sample)
            if len(buf) == self.batch_size:
                yield samples_to_minibatch(buf, self.feature_padding,
                                           self.label_padding)
                buf = []
        if buf and not self.drop_remainder:
            yield samples_to_minibatch(buf, self.feature_padding,
                                       self.label_padding)


class Normalizer(Transformer):
    """(x - mean) / std on Sample features (image-pipeline analogue:
    dataset/image/BGRImgNormalizer.scala)."""

    def __init__(self, mean, std):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def apply_one(self, s):
        return Sample((np.asarray(s.feature, np.float32) - self.mean)
                      / self.std, s.label)

    def apply(self, it):
        return (self.apply_one(s) for s in it)
