"""Transformer chain: composable Iterator -> Iterator stages.

Reference: dataset/Transformer.scala:44 (``Transformer[A, B] =
Iterator[A] => Iterator[B]`` with ``->`` composition) and the
SampleToMiniBatch batcher (:211).
"""

from typing import Iterator, Optional

import numpy as np

from bigdl_tpu.dataset.minibatch import (PaddingParam, Sample,
                                         samples_to_minibatch)


class Transformer:
    """apply(iterator) -> iterator; compose with ``a >> b`` (reference ``->``)."""

    def apply(self, it: Iterator) -> Iterator:
        raise NotImplementedError

    def __call__(self, it):
        return self.apply(it)

    def __rshift__(self, other: "Transformer") -> "ChainedTransformer":
        return ChainedTransformer(self, other)


class ChainedTransformer(Transformer):
    def __init__(self, first, second):
        self.first, self.second = first, second

    def apply(self, it):
        return self.second.apply(self.first.apply(it))


class FnTransformer(Transformer):
    """Wrap a per-element function."""

    def __init__(self, fn):
        self.fn = fn

    def apply(self, it):
        return (self.fn(x) for x in it)


class SampleToMiniBatch(Transformer):
    """Group Samples into MiniBatches (reference: Transformer.scala:211).

    Incomplete trailing batches are dropped when ``drop_remainder`` -- the
    distributed path requires static shapes for jit, matching the
    reference's fixed batchSize contract.
    """

    def __init__(self, batch_size: int, feature_padding: Optional[PaddingParam] = None,
                 label_padding: Optional[PaddingParam] = None,
                 drop_remainder: bool = True):
        self.batch_size = batch_size
        self.feature_padding = feature_padding
        self.label_padding = label_padding
        self.drop_remainder = drop_remainder

    def apply(self, it):
        buf = []
        for sample in it:
            buf.append(sample)
            if len(buf) == self.batch_size:
                yield samples_to_minibatch(buf, self.feature_padding,
                                           self.label_padding)
                buf = []
        if buf and not self.drop_remainder:
            yield samples_to_minibatch(buf, self.feature_padding,
                                       self.label_padding)


class Normalizer(Transformer):
    """(x - mean) / std on Sample features (image-pipeline analogue:
    dataset/image/BGRImgNormalizer.scala)."""

    def __init__(self, mean, std):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def apply(self, it):
        for s in it:
            yield Sample((np.asarray(s.feature, np.float32) - self.mean)
                         / self.std, s.label)
