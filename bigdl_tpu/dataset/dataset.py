"""DataSet abstractions.

Reference: dataset/DataSet.scala:49 (``LocalDataSet``: data(train) iterator +
size + shuffle), :113/:167 (``DistributedDataSet`` over RDDs, cached per
partition).

TPU-native: the host feeds one global batch per step; under data parallelism
each host materialises only its shard (DistributedDataSet below), matching
the reference's one-task-per-node ingest (ZippedPartitionsWithLocalityRDD).
No Spark dependency -- any indexable source works; a Spark RDD can be
adapted by collecting partition iterators host-side.
"""

from typing import Callable, Iterator, List, Optional, Sequence

import numpy as np

from bigdl_tpu.dataset.minibatch import Sample
from bigdl_tpu.dataset.transformer import Transformer


class AbstractDataSet:
    def data(self, train: bool) -> Iterator:
        raise NotImplementedError

    def size(self) -> int:
        raise NotImplementedError

    def shuffle(self):
        pass

    def position_state(self):
        """Serializable shuffle/order state for mid-epoch checkpoint
        resume (docs/robustness.md): everything needed so that a fresh
        ``data(train=True)`` iterator replays THIS epoch's element
        order, and future ``shuffle()`` calls continue the same
        shuffle-RNG stream.  ``None`` (the default) marks a source that
        cannot restore its position -- resume then restarts the epoch
        from the top with a warning instead of bit-matching the
        uninterrupted run."""
        return None

    def restore_position(self, state):
        """Restore a ``position_state()`` snapshot.  Only called with a
        state this class (or its base) produced."""
        raise NotImplementedError(
            f"{type(self).__name__} produced no position_state to "
            "restore")

    def transform(self, transformer: Transformer) -> "TransformedDataSet":
        return TransformedDataSet(self, transformer)

    def __rshift__(self, transformer: Transformer):
        """Reference's ``->`` composition (dataset/DataSet.scala:87)."""
        return self.transform(transformer)

    def prefetch(self, num_workers: int = 2, queue_depth: int = 4):
        """Run this dataset's transformer chain in background worker
        threads feeding a bounded queue (``dataset/prefetch.py``) --
        the TPU-native analogue of the reference's per-partition Spark
        task threads.  Terminal: apply AFTER the full ``>>`` chain."""
        from bigdl_tpu.dataset.prefetch import PrefetchDataSet
        return PrefetchDataSet(self, num_workers=num_workers,
                               queue_depth=queue_depth)


class LocalDataSet(AbstractDataSet):
    """In-memory dataset over a list/array of elements (reference:
    dataset/DataSet.scala:49, LocalArrayDataSet)."""

    def __init__(self, data: Sequence, shuffle_on_epoch: bool = True, seed: int = 0):
        self._data = list(data)
        self._index = np.arange(len(self._data))
        self.shuffle_on_epoch = shuffle_on_epoch
        self._rng = np.random.default_rng(seed)

    def size(self) -> int:
        return len(self._data)

    def shuffle(self):
        self._rng.shuffle(self._index)

    def data(self, train: bool) -> Iterator:
        if train:
            # infinite looping iterator like the reference's train=true path
            def gen():
                while True:
                    for i in self._index:
                        yield self._data[i]
            return gen()
        return (self._data[i] for i in range(len(self._data)))

    def position_state(self):
        """Current epoch permutation + the shuffle RNG stream position:
        restoring both makes a fresh iterator replay this epoch's order
        AND keeps every future reshuffle identical to the uninterrupted
        run's."""
        return {"kind": "local", "index": np.asarray(self._index).copy(),
                "rng_state": self._rng.bit_generator.state}

    def restore_position(self, state):
        if state.get("kind") != "local" or \
                len(state["index"]) != len(self._data):
            raise ValueError(
                f"dataset position state does not match this dataset "
                f"({len(state.get('index', ()))} indexed elements vs "
                f"{len(self._data)} held)")
        self._index = np.asarray(state["index"]).copy()
        self._rng.bit_generator.state = state["rng_state"]


class TransformedDataSet(AbstractDataSet):
    def __init__(self, base: AbstractDataSet, transformer: Transformer):
        self.base = base
        self.transformer = transformer

    def size(self):
        return self.base.size()

    def shuffle(self):
        self.base.shuffle()

    def data(self, train: bool):
        return self.transformer.apply(self.base.data(train))

    def position_state(self):
        return self.base.position_state()

    def restore_position(self, state):
        self.base.restore_position(state)


class DistributedDataSet(LocalDataSet):
    """Host-sharded dataset for multi-host training.

    Each process keeps records where ``index % num_shards == shard`` -- the
    analogue of the reference's cached per-partition arrays
    (dataset/DataSet.scala:243 CachedDistriDataSet).  ``size`` reports the
    *global* count so epoch accounting matches the reference.
    """

    def __init__(self, data: Sequence, shard: int = 0, num_shards: int = 1,
                 shuffle_on_epoch: bool = True, seed: int = 0):
        self._global_size = len(data)
        local = [x for i, x in enumerate(data) if i % num_shards == shard]
        super().__init__(local, shuffle_on_epoch, seed + shard)
        self.shard = shard
        self.num_shards = num_shards

    def size(self):
        return self._global_size

    def local_size(self):
        """Host-sharded marker + per-host record count (multi-host
        DistriOptimizer requires datasets exposing this)."""
        return len(self._data)


def array_dataset(features: np.ndarray, labels: Optional[np.ndarray] = None,
                  **kw) -> LocalDataSet:
    """DataSet.array analogue (reference: dataset/DataSet.scala:322)."""
    if labels is None:
        samples = [Sample(f) for f in features]
    else:
        samples = [Sample(f, l) for f, l in zip(features, labels)]
    return LocalDataSet(samples, **kw)
