"""MNIST ingestion (reference: models/lenet/Train.scala + dataset/DataSet
SeqFileFolder/mnist loaders; python analogue pyspark/bigdl/dataset/mnist.py).

Reads the standard idx-ubyte files when present; ``synthetic_mnist``
generates a deterministic class-separable stand-in for tests/benchmarks in
environments with no dataset access.
"""

import gzip
import os
import struct

import numpy as np

TRAIN_MEAN, TRAIN_STD = 0.13066047740239506, 0.3081078

_FILES = {
    "train_images": "train-images-idx3-ubyte",
    "train_labels": "train-labels-idx1-ubyte",
    "test_images": "t10k-images-idx3-ubyte",
    "test_labels": "t10k-labels-idx1-ubyte",
}


def _read_idx(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(f">{ndim}I", f.read(4 * ndim))
        return np.frombuffer(f.read(), np.uint8).reshape(dims)


def load_mnist(folder: str, train: bool = True):
    """-> (images (N,28,28) float32 in [0,1], labels (N,) int32)."""
    key = "train" if train else "test"
    imgs = labels = None
    for suffix in ("", ".gz"):
        ipath = os.path.join(folder, _FILES[f"{key}_images"] + suffix)
        lpath = os.path.join(folder, _FILES[f"{key}_labels"] + suffix)
        if os.path.exists(ipath) and os.path.exists(lpath):
            imgs, labels = _read_idx(ipath), _read_idx(lpath)
            break
    if imgs is None:
        raise FileNotFoundError(f"MNIST idx files not found under {folder}")
    return imgs.astype(np.float32) / 255.0, labels.astype(np.int32)


def synthetic_mnist(n: int = 2048, num_classes: int = 10, seed: int = 7):
    """Deterministic separable digits: class-specific Gaussian blobs.

    Each class lights up a distinct 2-D Gaussian bump on the 28x28 canvas
    plus noise -- learnable by LeNet in a handful of steps, which is what the
    convergence tests need.
    """
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, n).astype(np.int32)
    yy, xx = np.mgrid[0:28, 0:28].astype(np.float32)
    images = np.empty((n, 28, 28), np.float32)
    for c in range(num_classes):
        cy, cx = 6 + 3 * (c // 5) * 4, 4 + (c % 5) * 5
        bump = np.exp(-((yy - cy) ** 2 + (xx - cx) ** 2) / 18.0)
        mask = labels == c
        k = int(mask.sum())
        images[mask] = bump[None] + 0.3 * rng.standard_normal(
            (k, 28, 28)).astype(np.float32)
    return np.clip(images, 0.0, 1.0), labels
