"""Directory-of-images ingestion (reference: dataset/DataSet.scala:420
``ImageFolder``: path/label-dir/img files -> LocalImgData, labels assigned
by sorted directory name, 1-based in the reference -- 0-based here, the
pyspark compat layer shifts).

Decode is host-side via Pillow (the TPU analogue of the reference's
OpenCV JNI path, SURVEY.md 2.8: image decode never touches the chip).
"""

import os

import numpy as np

from bigdl_tpu.dataset.dataset import LocalDataSet
from bigdl_tpu.dataset.minibatch import Sample

_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".ppm", ".gif")


def find_images(folder):
    """-> sorted [(path, class_index)], class order = sorted dir names
    (reference ImageFolder.paths assigns labels by directory scan order)."""
    classes = sorted(
        d for d in os.listdir(folder)
        if os.path.isdir(os.path.join(folder, d)))
    if not classes:
        raise FileNotFoundError(f"no class directories under {folder}")
    out = []
    for idx, cls in enumerate(classes):
        cdir = os.path.join(folder, cls)
        for name in sorted(os.listdir(cdir)):
            if name.lower().endswith(_EXTS):
                out.append((os.path.join(cdir, name), idx))
    return out, classes


def decode_image(path, size=None):
    """-> (H, W, 3) float32 RGB in [0,1]; optional (h, w) resize."""
    from PIL import Image

    with Image.open(path) as im:
        im = im.convert("RGB")
        if size is not None:
            im = im.resize((size[1], size[0]), Image.BILINEAR)
        return np.asarray(im, np.float32) / 255.0


class ImageFolderDataSet(LocalDataSet):
    """Lazily-decoded folder dataset: elements are Samples with the decoded
    image as feature (reference: DataSet.ImageFolder.images reads eagerly;
    we decode per epoch on the host input thread instead -- HBM never sees
    undecoded bytes)."""

    def __init__(self, folder, size=None, shuffle_on_epoch=True, seed=0):
        items, self.classes = find_images(folder)
        self._size_hw = size
        super().__init__(items, shuffle_on_epoch=shuffle_on_epoch, seed=seed)

    def data(self, train=True):
        for path, label in super().data(train):
            yield Sample(decode_image(path, self._size_hw),
                         np.int32(label))


def image_folder(folder, size=None, **kw):
    """Factory mirroring DataSet.ImageFolder (DataSet.scala:420)."""
    return ImageFolderDataSet(folder, size=size, **kw)
