"""Hadoop SequenceFile ingestion (the reference's ImageNet storage format).

Reference: dataset/DataSet.scala:482 ``SeqFileFolder`` -- reads sequence
files of (Text key, Text value) where the key text is "name\nlabel" (or
just "label") and the value holds the raw image bytes; records become
ByteRecord(bytes, label) (readLabel at DataSet.scala:508).

This is a pure-python parser of the on-disk format (SequenceFile v6,
uncompressed -- the layout produced by the reference's documented ImageNet
prep), plus a writer for fixtures.  Wire layout:

    "SEQ" + version(1B)
    key class name, value class name           (java writeUTF: u16 len + utf8)
    compressed(1B bool), blockCompressed(1B bool)
    metadata count (int32 BE) + (TextPair)*
    sync marker (16B)
    records: recordLen(int32 BE) keyLen(int32 BE) key value
             recordLen == -1 -> 16-byte sync marker follows
    Text serialisation: hadoop VInt length + utf8 bytes
"""

import io
import os
import struct

import numpy as np

_TEXT = "org.apache.hadoop.io.Text"


def _read_vint(f):
    """Hadoop WritableUtils.readVLong."""
    first = f.read(1)[0]
    b = first - 256 if first > 127 else first
    if -112 <= b <= 127:
        return b
    length = (-112 - b) if b >= -120 else (-120 - b)
    val = 0
    for _ in range(length):
        val = (val << 8) | f.read(1)[0]
    return ~val if b < -120 else val


def _write_vint(n):
    """Hadoop WritableUtils.writeVLong (non-negative sizes only here)."""
    if -112 <= n <= 127:
        return bytes([n & 0xFF])
    length = 0
    tmp = n
    while tmp:
        length += 1
        tmp >>= 8
    out = bytes([(-112 - length) & 0xFF])
    return out + n.to_bytes(length, "big")


def _read_utf(f):
    (ln,) = struct.unpack(">H", f.read(2))
    return f.read(ln).decode("utf-8")


def _write_utf(s):
    b = s.encode("utf-8")
    return struct.pack(">H", len(b)) + b


def _read_text(buf):
    f = io.BytesIO(buf)
    ln = _read_vint(f)
    return f.read(ln).decode("utf-8", errors="replace")


def _write_text(s):
    b = s.encode("utf-8") if isinstance(s, str) else bytes(s)
    return _write_vint(len(b)) + b


class SequenceFileReader:
    """Iterate (key_bytes, value_bytes) records from one sequence file."""

    def __init__(self, path):
        self.path = path

    def __iter__(self):
        with open(self.path, "rb") as f:
            magic = f.read(3)
            if magic != b"SEQ":
                raise ValueError(f"{self.path}: not a SequenceFile")
            version = f.read(1)[0]
            if version < 5:
                raise NotImplementedError(
                    f"SequenceFile version {version} (< 5) unsupported")
            key_cls = _read_utf(f)
            val_cls = _read_utf(f)
            compressed = f.read(1)[0] != 0
            block_compressed = f.read(1)[0] != 0
            if compressed or block_compressed:
                raise NotImplementedError(
                    f"{self.path}: compressed SequenceFiles unsupported "
                    f"(the reference's ImageNet prep writes uncompressed)")
            (meta_count,) = struct.unpack(">I", f.read(4))
            for _ in range(meta_count):
                _read_text(f)            # metadata key
                _read_text(f)            # metadata value
            sync = f.read(16)
            while True:
                head = f.read(4)
                if len(head) < 4:
                    return
                (rec_len,) = struct.unpack(">i", head)
                if rec_len == -1:        # sync marker
                    marker = f.read(16)
                    if marker != sync:
                        raise ValueError(f"{self.path}: bad sync marker")
                    continue
                (key_len,) = struct.unpack(">i", f.read(4))
                key = f.read(key_len)
                value = f.read(rec_len - key_len)
                yield key, value


class SequenceFileWriter:
    """Write (Text key, Text value) records (uncompressed, v6)."""

    def __init__(self, path, sync_interval=10):
        self._f = open(path, "wb")
        self._sync = os.urandom(16)
        self._count = 0
        self._interval = sync_interval
        self._f.write(b"SEQ" + bytes([6]))
        self._f.write(_write_utf(_TEXT))
        self._f.write(_write_utf(_TEXT))
        self._f.write(bytes([0, 0]))             # not compressed
        self._f.write(struct.pack(">I", 0))      # no metadata
        self._f.write(self._sync)

    def append(self, key: str, value: bytes):
        if self._count and self._count % self._interval == 0:
            self._f.write(struct.pack(">i", -1))
            self._f.write(self._sync)
        kb = _write_text(key)
        vb = _write_text(value)
        self._f.write(struct.pack(">i", len(kb) + len(vb)))
        self._f.write(struct.pack(">i", len(kb)))
        self._f.write(kb)
        self._f.write(vb)
        self._count += 1

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


def read_label(key_text: str) -> str:
    """Key text 'name\nlabel' or 'label' -> label
    (reference: SeqFileFolder.readLabel, DataSet.scala:508)."""
    parts = key_text.split("\n")
    return parts[0] if len(parts) == 1 else parts[1]


def read_name(key_text: str) -> str:
    parts = key_text.split("\n")
    if len(parts) < 2:
        raise ValueError("key in seq file only contains label, no name")
    return parts[0]


def find_seq_files(folder):
    """Sorted .seq files under a folder -- or the file itself when given
    a single .seq path (reference: findFiles, DataSet.scala:594)."""
    if os.path.isfile(folder):
        return [folder]
    out = [os.path.join(folder, f) for f in sorted(os.listdir(folder))
           if f.endswith(".seq")]
    if not out:
        raise FileNotFoundError(f"no .seq files under {folder}")
    return out


def read_byte_records(folder, class_num=None):
    """-> list of (image_bytes, float label) over every .seq file
    (reference: SeqFileFolder.files -> ByteRecord, DataSet.scala:535-543).
    """
    records = []
    for path in find_seq_files(folder):
        for key, value in SequenceFileReader(path):
            label = float(read_label(_read_text(key)))
            if class_num is not None and label > class_num:
                continue
            # value is a serialised Text: VInt length prefix + bytes
            f = io.BytesIO(value)
            ln = _read_vint(f)
            records.append((f.read(ln), label))
    return records
