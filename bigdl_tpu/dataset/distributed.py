"""Partitioned (Spark-style) distributed ingest.

Reference: dataset/DataSet.scala:167 (``DistributedDataSet`` over RDDs)
and :243 (``CachedDistriDataSet``: per-partition cached arrays, shuffled
within partitions, locality-aware zip via
spark-version/2.0 ZippedPartitionsWithLocalityRDD).

TPU-native translation: partitions are an *ingest-side* concept.  Each
HOST owns the partitions congruent to its process index — the locality
analogue: records are cached on the host that consumes them — caches
them on first touch, reshuffles *within* its cache at epoch boundaries
(the reference shuffles within partitions, not globally), and feeds the
per-host staging pipeline of ``DistriOptimizer``.  No JVM in the loop: a
pyspark RDD or DataFrame (when pyspark is installed) is just one
``PartitionedSource``; anything implementing the three-method protocol
(``num_partitions`` / ``partition`` / ``count``) works the same.
"""

from typing import Optional, Sequence

from bigdl_tpu.dataset.dataset import AbstractDataSet


class PartitionedSource:
    """Protocol for partitioned record sources (duck-typed; subclassing
    is optional)."""

    def num_partitions(self) -> int:
        raise NotImplementedError

    def partition(self, idx: int):
        """Iterable of records in partition ``idx``."""
        raise NotImplementedError

    def count(self) -> int:
        """Global record count across ALL partitions."""
        raise NotImplementedError


class ListPartitionSource(PartitionedSource):
    """In-memory partitions: the protocol reference implementation (and
    the test double for Spark-less environments)."""

    def __init__(self, partitions: Sequence[Sequence]):
        self._parts = [list(p) for p in partitions]

    def num_partitions(self):
        return len(self._parts)

    def partition(self, idx):
        return self._parts[idx]

    def count(self):
        return sum(len(p) for p in self._parts)


class RDDSource(PartitionedSource):
    """A pyspark RDD as a partitioned source.  Fetches one partition at a
    time (``sc.runJob`` with a partition list — the per-partition analogue
    of the reference's cached ``rdd.persist()``), so a host never pulls
    the whole dataset."""

    def __init__(self, rdd):
        self.rdd = rdd
        self._n = rdd.getNumPartitions()
        self._count = None

    def num_partitions(self):
        return self._n

    def partition(self, idx):
        sc = self.rdd.context
        (records,) = sc.runJob(self.rdd, lambda it: [list(it)], [idx])
        return records

    def count(self):
        if self._count is None:
            self._count = self.rdd.count()
        return self._count


def is_partitioned(obj) -> bool:
    """True for objects :func:`source_of` accepts as partitioned sources
    by duck type (RDD / DataFrame / the three-method protocol); plain
    record lists are NOT partitioned (even though an explicit
    list-of-partitions coerces via ``source_of``)."""
    return (hasattr(obj, "getNumPartitions") or hasattr(obj, "rdd")
            or (hasattr(obj, "num_partitions")
                and hasattr(obj, "partition")))


def source_of(obj) -> PartitionedSource:
    """Coerce an RDD / DataFrame / list-of-partitions / PartitionedSource
    to a PartitionedSource."""
    if hasattr(obj, "num_partitions") and hasattr(obj, "partition"):
        return obj
    if hasattr(obj, "rdd"):                      # pyspark DataFrame
        return RDDSource(obj.rdd.map(lambda row: row))
    if hasattr(obj, "getNumPartitions"):         # pyspark RDD
        return RDDSource(obj)
    if isinstance(obj, (list, tuple)) and obj \
            and isinstance(obj[0], (list, tuple)):
        return ListPartitionSource(obj)
    raise TypeError(
        f"cannot interpret {type(obj).__name__} as a partitioned source; "
        "pass a pyspark RDD/DataFrame, a list of partitions, or any "
        "object with num_partitions()/partition(i)/count()")


class PartitionedDataSet(AbstractDataSet):
    """Host-sharded dataset over a ``PartitionedSource``.

    Partition ``p`` belongs to the host with ``p % num_hosts ==
    host_index`` (defaults: ``jax.process_count()`` /
    ``jax.process_index()``).  Partitions are cached host-side on first
    touch; ``shuffle()`` reshuffles within the cache; ``size()`` reports
    the GLOBAL record count so the optimizer's epoch accounting matches
    the reference's (record_count is advanced by the global batch).
    Compose transformers with ``>>`` as with any dataset.
    """

    def __init__(self, source, host_index: Optional[int] = None,
                 num_hosts: Optional[int] = None, seed: int = 0):
        import numpy as np

        self.source = source_of(source)
        if num_hosts is None or host_index is None:
            import jax
            num_hosts = jax.process_count() if num_hosts is None \
                else num_hosts
            host_index = jax.process_index() if host_index is None \
                else host_index
        if not 0 <= host_index < num_hosts:
            raise ValueError(f"host_index {host_index} outside "
                             f"[0, {num_hosts})")
        self.host_index = host_index
        self.num_hosts = num_hosts
        self.my_partitions = [
            p for p in range(self.source.num_partitions())
            if p % num_hosts == host_index]
        if not self.my_partitions:
            # a host with no data would spin forever in the train
            # iterator; repartition the source to >= num_hosts partitions
            raise ValueError(
                f"host {host_index}/{num_hosts} owns no partitions "
                f"(source has {self.source.num_partitions()}); "
                f"repartition to at least {num_hosts} partitions")
        self._rng = np.random.default_rng(seed + host_index)
        self._cache = None        # list of per-partition record lists
        self._order = None        # list of per-partition index arrays

    def _materialize(self):
        import numpy as np

        if self._cache is None:
            self._cache = [list(self.source.partition(p))
                           for p in self.my_partitions]
            self._order = [np.arange(len(part)) for part in self._cache]
        return self._cache

    def size(self):
        return self.source.count()

    def local_size(self):
        return sum(len(p) for p in self._materialize())

    def shuffle(self):
        """Within-partition reshuffle (reference: CachedDistriDataSet
        shuffles each cached partition array, DataSet.scala:243)."""
        self._materialize()
        for i, part in enumerate(self._cache):
            self._order[i] = self._rng.permutation(len(part))

    def data(self, train: bool):
        parts = self._materialize()

        if not train:
            def once():
                for part, order in zip(parts, self._order):
                    for i in order:
                        yield part[i]
            return once()

        def forever():
            while True:
                # re-read the order arrays every epoch so a shuffle()
                # between epochs takes effect (LocalDataSet idiom)
                for part, order in zip(parts, self._order):
                    for i in order:
                        yield part[i]
        return forever()


def rdd_dataset(rdd, **kw) -> PartitionedDataSet:
    """``DataSet.rdd`` analogue (reference: dataset/DataSet.scala:167)."""
    return PartitionedDataSet(rdd, **kw)
