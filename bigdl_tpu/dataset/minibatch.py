"""Sample / MiniBatch.

Reference: dataset/Sample.scala:32 (feature+label tensors),
dataset/MiniBatch.scala:34 (slice/getInput/getTarget),
dataset/MiniBatch.scala:523 (PaddingParam feature padding).

Host-side data is numpy; conversion to device arrays happens once per batch
at the jit boundary (minimising host->HBM transfers).
"""

from typing import Any, List, Optional, Sequence

import numpy as np


class Sample:
    """One training example: feature activity + label activity."""

    def __init__(self, feature, label=None):
        self.feature = feature
        self.label = label

    def __repr__(self):
        f = np.shape(self.feature)
        l = None if self.label is None else np.shape(self.label)
        return f"Sample(feature={f}, label={l})"


class MiniBatch:
    """A batched set of samples (reference: MiniBatch.scala:34)."""

    def __init__(self, input, target=None):
        self.input = input
        self.target = target

    def get_input(self):
        return self.input

    def get_target(self):
        return self.target

    def tree(self):
        """``(input, target)`` as ONE pytree (target may be None -- an
        empty subtree), so the host->device move is a single
        ``jax.device_put`` over the whole batch instead of a blocking
        per-leaf conversion."""
        return self.input, self.target

    def size(self) -> int:
        leaf = self.input
        while isinstance(leaf, (tuple, list)):
            leaf = leaf[0]
        return leaf.shape[0]

    def slice(self, offset, length) -> "MiniBatch":
        def cut(x):
            if isinstance(x, (tuple, list)):
                return type(x)(cut(e) for e in x)
            return x[offset:offset + length]

        return MiniBatch(cut(self.input),
                         None if self.target is None else cut(self.target))

    def pad_to(self, batch_to: int, pad_target: bool = True) -> "MiniBatch":
        """Zero-pad the batch axis up to ``batch_to`` rows (the serving
        bucket ladder's Sample->padded-MiniBatch path): padded rows are
        inert in eval mode (batch-row-independent layers) and the
        caller slices them off the output.  Identity when already
        sized; a SMALLER target is an error, not a truncation.

        ``pad_target=False`` passes the target through UNTOUCHED (its
        batch axis stays at the real row count) -- the predict path
        never reads it, so padding it would be a wasted copy and an
        object-dtype label tree must not veto padding the input."""
        n = self.size()
        if batch_to == n:
            return self
        if batch_to < n:
            raise ValueError(
                f"pad_to({batch_to}) cannot shrink a batch of {n}")
        # lazy import: serving imports this module at load time
        from bigdl_tpu.serving.buckets import pad_batch_axis

        def check(x, label):
            if isinstance(x, (tuple, list)):
                for e in x:
                    check(e, label)
            elif np.asarray(x).dtype == object:   # e.g. SparseTensor leaves
                raise TypeError(
                    f"pad_to cannot zero-pad non-array {label} leaves "
                    f"({type(x).__name__})")

        check(self.input, "input")
        target = self.target
        if pad_target and target is not None:
            check(target, "target")
            target = pad_batch_axis(target, batch_to)
        return MiniBatch(pad_batch_axis(self.input, batch_to), target)


class PaddingParam:
    """Pad variable-length features to a common shape
    (reference: MiniBatch.scala:523 PaddingParam)."""

    def __init__(self, padding_value=0.0, fixed_length: Optional[int] = None):
        self.padding_value = padding_value
        self.fixed_length = fixed_length


def _stack(arrays: Sequence[np.ndarray], padding: Optional[PaddingParam]):
    """Stack, padding the first (time) axis if lengths differ."""
    shapes = {a.shape for a in arrays}
    if len(shapes) == 1 and (padding is None or padding.fixed_length is None):
        return np.stack(arrays)
    if padding is None:
        padding = PaddingParam()
    max_len = max(a.shape[0] for a in arrays)
    if padding.fixed_length is not None:
        max_len = padding.fixed_length
    out_shape = (len(arrays), max_len) + arrays[0].shape[1:]
    out = np.full(out_shape, padding.padding_value, dtype=arrays[0].dtype)
    for i, a in enumerate(arrays):
        out[i, : a.shape[0]] = a[:max_len]
    return out


def samples_to_minibatch(
    samples: List[Sample],
    feature_padding: Optional[PaddingParam] = None,
    label_padding: Optional[PaddingParam] = None,
) -> MiniBatch:
    """Batch a list of Samples (reference: SampleToMiniBatch transformer)."""
    first = samples[0]
    if isinstance(first.feature, (tuple, list)):
        input = tuple(
            _stack([s.feature[i] for s in samples], feature_padding)
            for i in range(len(first.feature))
        )
    else:
        input = _stack([s.feature for s in samples], feature_padding)
    target = None
    if first.label is not None:
        if isinstance(first.label, (tuple, list)):
            target = tuple(
                _stack([s.label[i] for s in samples], label_padding)
                for i in range(len(first.label))
            )
        else:
            target = _stack([np.asarray(s.label) for s in samples], label_padding)
    return MiniBatch(input, target)


class SparseMiniBatch(MiniBatch):
    """MiniBatch whose features are batched into padded-COO SparseTensors
    (reference: dataset/MiniBatch.scala:588 SparseMiniBatch).

    ``capacity`` fixes the nnz padding so every batch reuses one compiled
    program; default is the dense element count of the batch.
    """

    @staticmethod
    def of(samples: List[Sample], capacity: Optional[int] = None,
           sparse_feature: bool = True) -> "SparseMiniBatch":
        from bigdl_tpu.nn.sparse import sparse_stack

        first = samples[0]
        if sparse_feature:
            if isinstance(first.feature, (tuple, list)):
                input = tuple(
                    sparse_stack([s.feature[i] for s in samples], capacity)
                    for i in range(len(first.feature))
                )
            else:
                input = sparse_stack([s.feature for s in samples], capacity)
        else:
            input = _stack([s.feature for s in samples], None)
        target = None
        if first.label is not None:
            target = _stack([np.asarray(s.label) for s in samples], None)
        return SparseMiniBatch(input, target)
