"""Asynchronous input pipeline: background transform workers + bounded queue.

The reference gets ingest/compute overlap for free from Spark's
per-partition task threads (dataset/DataSet.scala:243 CachedDistriDataSet);
this TPU-native port feeds one global batch per step from the host, so
without this module every Python-side transform and host->device copy sits
on the step's critical path.  ``PrefetchDataSet`` wraps any
:class:`~bigdl_tpu.dataset.dataset.AbstractDataSet` (composing with
``TransformedDataSet``/``>>`` chains) and runs the transformer chain in
background threads feeding a bounded queue:

    producer ---> work queue ---> N workers (per-element stages)
                                      |
                              reorder-by-sequence
                                      |
                  assembler (order-dependent stages, e.g. SampleToMiniBatch)
                                      |
                        bounded output queue ---> training loop

Determinism: workers only run stages declaring ``apply_one`` (element-wise,
stateless across elements -- ``FnTransformer``, ``Normalizer``); their
outputs are reassembled in source order before the remaining stages apply
serially, so the batch sequence is IDENTICAL to the synchronous path for a
fixed seed.  Epoch-boundary reshuffles keep that guarantee because the
driver loop re-creates the iterator per epoch: ``shuffle()``/``data()``
retire the previous epoch's threads first and the fresh producer starts
from the newly shuffled index, exactly like the synchronous path.

Liveness: the round-3 deferred-fetch fix in
``BaseOptimizer._stage_next_batch`` is preserved -- nothing here pulls from
the training iterator eagerly past the bounded pipeline.  Host memory is
bounded end to end: ``queue_depth`` ready batches in the output queue plus
a reorder window of in-flight elements (the work queue, the reorder
buffer, and one element per worker) -- workers that run ahead of the
consumer WAIT instead of freewheeling the source into memory.
``shutdown()`` (called by the driver loop's ``finally``) drains and joins
every thread so no worker outlives training; the one exception is a
producer blocked inside a stream source's uninterruptible ``next()``,
which is left as a daemon rather than stalling shutdown.
"""

import logging
import queue
import threading
from typing import Iterator, List, Optional, Tuple

from bigdl_tpu.dataset.dataset import AbstractDataSet, TransformedDataSet
from bigdl_tpu.dataset.transformer import ChainedTransformer, Transformer

log = logging.getLogger("bigdl_tpu.dataset")

#: end-of-stream marker on the internal queues (never yielded to callers)
_DONE = object()

#: worker threads check the stop flag at this cadence while blocked
_POLL_S = 0.05


def _flatten_chain(transformer: Transformer) -> List[Transformer]:
    if isinstance(transformer, ChainedTransformer):
        return (_flatten_chain(transformer.first)
                + _flatten_chain(transformer.second))
    return [transformer]


def decompose(dataset: AbstractDataSet) -> Tuple[AbstractDataSet,
                                                 List[Transformer]]:
    """Walk nested ``TransformedDataSet`` wrappers -> (source, stages in
    application order), flattening ``ChainedTransformer`` compositions."""
    stages: List[Transformer] = []
    while isinstance(dataset, TransformedDataSet):
        stages = _flatten_chain(dataset.transformer) + stages
        dataset = dataset.base
    return dataset, stages


def split_parallel(stages: List[Transformer]):
    """Split the chain at the first order-dependent stage: the prefix of
    ``apply_one`` stages fans out across workers; the suffix (batching,
    stages opting out via ``parallel_safe=False``) runs serially on the
    reordered stream."""
    fns = []
    for i, t in enumerate(stages):
        fn = getattr(t, "apply_one", None)
        if not callable(fn):
            return fns, stages[i:]
        fns.append(fn)
    return fns, []


class _PrefetchIterator:
    """One epoch-stream's worth of pipeline threads.

    Threads: 1 producer (pulls the source iterator -- the ONLY consumer of
    the underlying data order), ``num_workers`` transform workers, and 1
    assembler that restores source order and applies the serial suffix
    stages into the bounded output queue.  All are daemons named
    ``bigdl-prefetch-*`` and stop-flag aware, so ``close()`` converges in
    ~``_POLL_S`` even with full queues; the first exception from any
    thread is re-raised in the consumer's ``next()`` (never a silent
    hang).
    """

    def __init__(self, source_iter: Iterator, per_element, suffix,
                 num_workers: int, queue_depth: int):
        self._source_iter = source_iter
        self._per_element = list(per_element)
        self._suffix = list(suffix)
        self._num_workers = num_workers
        self._work_q = queue.Queue(maxsize=max(2 * num_workers, queue_depth))
        self._out = queue.Queue(maxsize=queue_depth)
        self._ready = {}              # seq -> transformed element
        #: reorder window: a worker holding seq >= _next_seq + _window
        #: waits before depositing, so when the consumer stalls the
        #: pipeline stops at (window + workers + queue_depth) buffered
        #: elements instead of freewheeling the source into host memory.
        #: FIFO task pickup means the waiters always hold the HIGHEST
        #: outstanding seqs, so _next_seq can always advance (no deadlock)
        self._window = self._work_q.maxsize
        self._next_seq = 0
        self._cond = threading.Condition()
        self._n_items: Optional[int] = None   # set when the source ends
        self._stop = threading.Event()
        #: producer is inside the source's (uninterruptible) next()
        self._reading = threading.Event()
        self._err: Optional[BaseException] = None
        self._threads = [
            threading.Thread(target=self._produce,
                             name="bigdl-prefetch-producer", daemon=True)]
        self._threads += [
            threading.Thread(target=self._work,
                             name=f"bigdl-prefetch-worker-{i}", daemon=True)
            for i in range(num_workers)]
        self._threads.append(
            threading.Thread(target=self._assemble,
                             name="bigdl-prefetch-assembler", daemon=True))
        for t in self._threads:
            t.start()

    # ----- thread bodies --------------------------------------------------- #
    def _put(self, q, item) -> bool:
        """Stop-aware blocking put; False when shut down mid-wait."""
        while not self._stop.is_set():
            try:
                q.put(item, timeout=_POLL_S)
                return True
            except queue.Full:
                continue
        return False

    def _fail(self, exc: BaseException):
        with self._cond:
            if self._err is None:
                self._err = exc
            self._stop.set()
            self._cond.notify_all()
        try:                          # wake a consumer blocked on get()
            self._out.put_nowait(_DONE)
        except queue.Full:
            pass

    def _produce(self):
        seq = 0
        try:
            while not self._stop.is_set():
                # the source read cannot be interrupted; flag it so
                # close() knows not to wait on a blocked stream source
                self._reading.set()
                try:
                    item = next(self._source_iter)
                except StopIteration:
                    break
                finally:
                    self._reading.clear()
                if not self._put(self._work_q, (seq, item)):
                    return
                seq += 1
            else:
                return                # shut down mid-stream
        except Exception as e:
            self._fail(e)
            return
        with self._cond:              # finite source exhausted (eval path)
            self._n_items = seq
            self._cond.notify_all()
        for _ in range(self._num_workers):
            if not self._put(self._work_q, _DONE):
                return

    def _work(self):
        while not self._stop.is_set():
            try:
                task = self._work_q.get(timeout=_POLL_S)
            except queue.Empty:
                continue
            if task is _DONE:
                return
            seq, item = task
            try:
                for fn in self._per_element:
                    item = fn(item)
            except Exception as e:
                self._fail(e)
                return
            with self._cond:
                # backpressure: far-ahead results wait for the consumer
                # (bounds the reorder buffer; see _window above)
                while (not self._stop.is_set()
                       and seq - self._next_seq >= self._window):
                    self._cond.wait(timeout=_POLL_S)
                if self._stop.is_set():
                    return
                self._ready[seq] = item
                self._cond.notify_all()

    def _ordered(self):
        """Yield worker outputs in SOURCE order (the determinism seam)."""
        while True:
            with self._cond:
                nxt = self._next_seq
                while True:
                    if self._stop.is_set():
                        return
                    if nxt in self._ready:
                        item = self._ready.pop(nxt)
                        self._next_seq = nxt + 1
                        self._cond.notify_all()   # wake waiting workers
                        break
                    if self._n_items is not None and nxt >= self._n_items:
                        return
                    self._cond.wait(timeout=_POLL_S)
            yield item

    def _assemble(self):
        try:
            stream = self._ordered()
            for t in self._suffix:
                stream = t.apply(stream)
            for item in stream:
                if not self._put(self._out, item):
                    return
        except Exception as e:
            self._fail(e)
            return
        self._put(self._out, _DONE)

    # ----- consumer side --------------------------------------------------- #
    def __iter__(self):
        return self

    def __next__(self):
        while True:
            if self._err is not None:
                err = self._err
                self.close()
                raise err
            try:
                item = self._out.get(timeout=_POLL_S)
            except queue.Empty:
                if self._stop.is_set() and self._err is None:
                    raise StopIteration
                continue
            if item is _DONE:
                if self._err is not None:
                    continue          # error sentinel: raise on next pass
                raise StopIteration
            return item

    def depth(self) -> int:
        """Current output-queue occupancy (0 = the training loop is about
        to block on the producers: a starved pipeline)."""
        return self._out.qsize()

    def close(self):
        """Stop and join every pipeline thread (drain semantics: queued
        items are discarded; the source iterator is simply abandoned).

        A producer blocked inside a stream source's ``next()`` cannot be
        interrupted from Python: it is left behind as a daemon (it dies
        with the process, or exits the moment the source yields) instead
        of stalling shutdown -- sources with an indefinitely-blocking
        read should arrange their own end-of-stream signal."""
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        for t in self._threads:
            if t is threading.current_thread():
                continue
            blocked_in_source = (t.name == "bigdl-prefetch-producer"
                                 and self._reading.is_set())
            t.join(timeout=0.2 if blocked_in_source else 5.0)
        alive = [t.name for t in self._threads
                 if t.is_alive() and t is not threading.current_thread()]
        if alive and self._reading.is_set() and \
                alive == ["bigdl-prefetch-producer"]:
            log.debug("prefetch producer left blocked in the source's "
                      "next(); daemon thread will exit with the source")
        elif alive:                   # pragma: no cover - defensive
            log.warning("prefetch threads failed to join: %s", alive)
        self._threads = []

    def __del__(self):                # pragma: no cover - GC backstop
        try:
            self._stop.set()
        except Exception:
            pass


class PrefetchDataSet(AbstractDataSet):
    """Run a dataset's transformer chain in background worker threads
    feeding a bounded queue, overlapping host-side input work with device
    compute.

        train = (array_dataset(x, y) >> Normalizer(m, s)
                 >> SampleToMiniBatch(128)).prefetch(num_workers=4)

    ``num_workers`` bounds transform parallelism (0 = fully synchronous
    passthrough, for A/B); ``queue_depth`` bounds ready batches held ahead
    of the training loop (host memory = queue_depth batches).  Training
    iterators (``data(train=True)``) are asynchronous; the evaluation
    stream (``train=False``) stays synchronous -- validation cadence is
    bursty and correctness-critical, and the serial path is trivially
    ordered and leak-free.

    One live training stream at a time: ``shuffle()`` / ``data(train=True)``
    retire the previous epoch's threads first (the driver loop re-creates
    the iterator each epoch), and ``shutdown()`` -- called by the driver
    loop when training ends, including the PREDICTED_END early-stop path --
    joins everything so no thread outlives the run.
    """

    def __init__(self, base: AbstractDataSet, num_workers: int = 2,
                 queue_depth: int = 4):
        if num_workers < 0:
            raise ValueError(f"num_workers must be >= 0, got {num_workers}")
        if queue_depth < 1:
            raise ValueError(f"queue_depth must be >= 1, got {queue_depth}")
        self.base = base
        self.num_workers = num_workers
        self.queue_depth = queue_depth
        self._live: Optional[_PrefetchIterator] = None

    def size(self) -> int:
        return self.base.size()

    def shuffle(self):
        # retire in-flight workers BEFORE the index mutates: discarded
        # prefetched elements belong to the pre-shuffle order, exactly the
        # elements the synchronous path never materialised
        self.shutdown()
        self.base.shuffle()

    def data(self, train: bool):
        if not train:
            return self.base.data(train=False)
        self.shutdown()
        if self.num_workers == 0:
            return self.base.data(train=True)
        source, stages = decompose(self.base)
        per_element, suffix = split_parallel(stages)
        self._live = _PrefetchIterator(
            source.data(train=True), per_element, suffix,
            self.num_workers, self.queue_depth)
        return self._live

    def shutdown(self):
        """Stop and join the live pipeline threads (idempotent)."""
        if self._live is not None:
            self._live.close()
            self._live = None

    def position_state(self):
        """Delegates to the source: the pipeline itself holds no order
        state -- workers fan out but the reorder stage + serial suffix
        (SampleToMiniBatch) keep the BATCH stream identical to the
        synchronous path, so "k batches consumed" pins the same source
        position either way (docs/robustness.md, mid-epoch resume)."""
        return self.base.position_state()

    def restore_position(self, state):
        # retire in-flight workers first: buffered elements belong to
        # the pre-restore order
        self.shutdown()
        self.base.restore_position(state)

    def queue_stats(self) -> Optional[Tuple[int, int]]:
        """``(occupancy, capacity)`` of the live output queue, or None
        when no asynchronous stream is active.  The driver loop samples
        this into each step event (``queue_depth`` / ``queue_capacity``)
        so ``tools/obs_report.py`` can distinguish a starved pipeline
        (occupancy pinned at 0) from a slow device (queue full)."""
        it = self._live
        if it is None:
            return None
        return it.depth(), self.queue_depth
