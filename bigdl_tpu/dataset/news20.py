"""20 Newsgroups + GloVe ingestion (reference:
pyspark/bigdl/dataset/news20.py -- downloads and parses the 20news-18828
tarball layout: one directory per newsgroup, one file per post; and
glove.6B word-vector text files).

No network here: the loaders parse the standard on-disk layouts; tests
build miniature fixtures in the same layout.
"""

import os

import numpy as np

CLASS_NUM = 20


def get_news20(folder):
    """Parse an extracted 20news tree: folder/<group>/<post-file>.

    -> list of (text, label) with labels 0-based by sorted group name
    (the pyspark original is 1-based; the bigdl compat layer shifts).
    """
    groups = sorted(
        d for d in os.listdir(folder)
        if os.path.isdir(os.path.join(folder, d)))
    if not groups:
        raise FileNotFoundError(f"no newsgroup directories under {folder}")
    texts = []
    for label, group in enumerate(groups):
        gdir = os.path.join(folder, group)
        for name in sorted(os.listdir(gdir)):
            path = os.path.join(gdir, name)
            if not os.path.isfile(path):
                continue
            with open(path, "rb") as f:
                texts.append((f.read().decode("latin-1"), label))
    return texts


def get_glove_w2v(path, dim=None):
    """Parse a glove.6B-style text file: 'word v1 v2 ... vN' per line.

    -> dict word -> np.float32 vector.  ``dim`` (if given) validates the
    vector width.
    """
    w2v = {}
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            parts = line.rstrip().split(" ")
            if len(parts) < 2:
                continue
            vec = np.asarray(parts[1:], np.float32)
            if dim is not None and vec.size != dim:
                raise ValueError(
                    f"glove vector width {vec.size} != expected {dim}")
            w2v[parts[0]] = vec
    return w2v
