"""CIFAR-10 ingestion in the standard binary format.

Reference: models/vgg/Train.scala + models/resnet/Train.scala load CIFAR-10
for the cifar recipes (the Scala side reads the python-pickle batches via
Spark; the canonical on-disk format here is the C binary version:
one record = 1 label byte + 3072 image bytes, R plane then G then B,
row-major 32x32 -- data_batch_{1..5}.bin / test_batch.bin).

``load_cifar10`` parses that format; ``synthetic_cifar10`` writes/creates a
deterministic separable stand-in (and can serialise it to the same binary
format) so convergence tests exercise the real parse path without network
access.
"""

import os

import numpy as np

# per-channel statistics of the real training set (reference:
# models/vgg/Train.scala normalisation constants are equivalent BGR means)
TRAIN_MEAN = (0.4914, 0.4822, 0.4465)
TRAIN_STD = (0.2470, 0.2435, 0.2616)

_RECORD = 1 + 3 * 32 * 32


def _parse_batch(path):
    raw = np.fromfile(path, np.uint8)
    if raw.size % _RECORD:
        raise ValueError(f"{path}: size {raw.size} not a multiple of "
                         f"{_RECORD}-byte CIFAR records")
    raw = raw.reshape(-1, _RECORD)
    labels = raw[:, 0].astype(np.int32)
    # (N, 3, 32, 32) planar -> NHWC float in [0,1]
    images = raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    return images.astype(np.float32) / 255.0, labels


def load_cifar10(folder, train=True):
    """-> (images (N,32,32,3) float32 in [0,1], labels (N,) int32)."""
    if train:
        files = sorted(
            f for f in os.listdir(folder)
            if f.startswith("data_batch") and f.endswith(".bin"))
    else:
        files = [f for f in ("test_batch.bin",)
                 if os.path.exists(os.path.join(folder, f))]
    if not files:
        raise FileNotFoundError(f"no CIFAR-10 .bin batches under {folder}")
    parts = [_parse_batch(os.path.join(folder, f)) for f in files]
    return (np.concatenate([p[0] for p in parts]),
            np.concatenate([p[1] for p in parts]))


def normalize(images, mean=TRAIN_MEAN, std=TRAIN_STD):
    return ((images - np.asarray(mean, np.float32))
            / np.asarray(std, np.float32)).astype(np.float32)


def synthetic_cifar10(n=2048, num_classes=10, seed=11):
    """Deterministic separable 32x32x3 blobs (same idea as synthetic_mnist):
    each class is a colored Gaussian bump at a class-specific position."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, n).astype(np.int32)
    yy, xx = np.mgrid[0:32, 0:32].astype(np.float32)
    images = np.empty((n, 32, 32, 3), np.float32)
    for c in range(num_classes):
        cy, cx = 8 + 12 * (c // 5), 4 + 6 * (c % 5)
        bump = np.exp(-((yy - cy) ** 2 + (xx - cx) ** 2) / 24.0)
        color = np.array([(c % 3 == 0), (c % 3 == 1), (c % 3 == 2)],
                         np.float32) * 0.7 + 0.3
        mask = labels == c
        k = int(mask.sum())
        images[mask] = (bump[..., None] * color
                        + 0.25 * rng.standard_normal((k, 32, 32, 3)))
    return np.clip(images, 0.0, 1.0).astype(np.float32), labels


def write_binary(path, images, labels):
    """Serialise (NHWC [0,1] float, int labels) to the CIFAR binary format
    (inverse of _parse_batch) -- used to build test fixtures."""
    imgs = np.clip(np.asarray(images) * 255.0, 0, 255).astype(np.uint8)
    imgs = imgs.transpose(0, 3, 1, 2).reshape(len(imgs), -1)  # planar RGB
    rec = np.concatenate(
        [np.asarray(labels, np.uint8)[:, None], imgs], axis=1)
    rec.tofile(path)
