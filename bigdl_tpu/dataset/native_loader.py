"""Native-accelerated batching + prefetching device feed.

Reference: MTLabeledBGRImgToBatch (dataset/image/MTLabeledBGRImgToBatch.scala)
-- the reference's multi-threaded batch assembly -- and the double-buffered
device-feed requirement in SURVEY.md section 7 ('Spark-as-ingest without
Spark-in-the-loop': pull host shards into a device-feed queue while the step
never leaves the device).

Two pieces:

- ``NativeBatcher``: gathers + channel-normalizes minibatches through the
  C++ kernel (native/batch_assembler.cpp, built on first use with g++,
  ctypes binding -- no pybind11).  Falls back to numpy transparently.
- ``Prefetcher``: a bounded background queue that assembles the next batches
  while the device is busy -- the ctypes call releases the GIL so assembly
  overlaps with the training step.
"""

import ctypes
import logging
import os
import queue
import subprocess
import threading
from typing import Iterator, Optional

import numpy as np

log = logging.getLogger("bigdl_tpu.dataset")

_LIB = None
_TRIED = False


def build_native_lib(name: str):
    """Build (if stale) and load ``native/<name>.cpp`` as
    ``build/lib<name>.so``.  Prebuilt artifacts from `make -C native` are
    used as-is; otherwise g++ compiles on demand; callers fall back to
    pure python/numpy when neither works."""
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    src = os.path.join(here, "native", f"{name}.cpp")
    out_dir = os.path.join(here, "build")
    so_path = os.path.join(out_dir, f"lib{name}.so")
    if (not os.path.exists(so_path)
            or os.path.getmtime(so_path) < os.path.getmtime(src)):
        os.makedirs(out_dir, exist_ok=True)
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-o", so_path, src,
             "-lpthread"],
            check=True, capture_output=True)
    return ctypes.CDLL(so_path)


def _build_and_load():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    try:
        lib = build_native_lib("batch_assembler")
        lib.bigdl_gather_normalize.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_int64, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.c_int,
            ctypes.POINTER(ctypes.c_float), ctypes.c_int,
        ]
        lib.bigdl_gather_labels.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_int64, ctypes.POINTER(ctypes.c_int32),
        ]
        _LIB = lib
    except Exception as e:  # toolchain missing -> numpy fallback
        log.warning("native batch assembler unavailable (%s); numpy fallback", e)
        _LIB = None
    return _LIB


def _fptr(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _i64ptr(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


class NativeBatcher:
    """Index-gather + normalize minibatches from a contiguous sample pool.

    ``features``: (N, ...) float32; ``labels``: (N, ...) int32 or None.
    """

    def __init__(self, features: np.ndarray, labels: Optional[np.ndarray],
                 mean=None, std=None, n_threads: int = 0):
        self.features = np.ascontiguousarray(features, np.float32)
        self.pool = self.features.reshape(len(features), -1)
        self.sample_shape = features.shape[1:]
        self.labels = (None if labels is None
                       else np.ascontiguousarray(labels, np.int32).reshape(
                           len(labels), -1))
        self.label_shape = () if labels is None else np.shape(labels)[1:]
        self.channels = 0
        self.mean = np.zeros(1, np.float32)
        self.std = np.ones(1, np.float32)
        if mean is not None:
            self.mean = np.ascontiguousarray(mean, np.float32)
            self.std = np.ascontiguousarray(std, np.float32)
            self.channels = self.mean.size
        self.n_threads = n_threads or min(8, os.cpu_count() or 1)
        self.lib = _build_and_load()

    def batch(self, indices: np.ndarray):
        indices = np.ascontiguousarray(indices, np.int64)
        b = len(indices)
        out = np.empty((b, self.pool.shape[1]), np.float32)
        if self.lib is not None:
            self.lib.bigdl_gather_normalize(
                _fptr(self.pool), _i64ptr(indices), b, self.pool.shape[1],
                _fptr(self.mean), _fptr(self.std), self.channels, _fptr(out),
                self.n_threads)
        else:
            out[:] = self.pool[indices]
            if self.channels:
                shaped = out.reshape((b,) + self.sample_shape)
                shaped -= self.mean
                shaped /= self.std
        x = out.reshape((b,) + self.sample_shape)
        if self.labels is None:
            return x, None
        lab = np.empty((b, self.labels.shape[1]), np.int32)
        if self.lib is not None:
            self.lib.bigdl_gather_labels(
                self.labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                _i64ptr(indices), b, self.labels.shape[1],
                lab.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        else:
            lab[:] = self.labels[indices]
        return x, lab.reshape((b,) + self.label_shape)


class Prefetcher:
    """Bounded background prefetch queue over any iterator (the
    double-buffered device feed; reference: MTLabeledBGRImgToBatch's
    producer threads)."""

    _DONE = object()

    def __init__(self, it: Iterator, depth: int = 2):
        self.q: "queue.Queue" = queue.Queue(maxsize=depth)
        self._thread = threading.Thread(target=self._run, args=(it,),
                                        daemon=True)
        self._thread.start()

    def _run(self, it):
        try:
            for item in it:
                self.q.put(item)
        finally:
            self.q.put(self._DONE)

    def __iter__(self):
        return self

    def __next__(self):
        item = self.q.get()
        if item is self._DONE:
            raise StopIteration
        return item


def prefetch(iterator: Iterator, depth: int = 2) -> Iterator:
    return Prefetcher(iterator, depth)
