"""MovieLens-1M ingestion (reference: pyspark/bigdl/dataset/movielens.py --
parses ml-1m/ratings.dat lines 'UserID::MovieID::Rating::Timestamp').

The loader parses the standard ml-1m layout from a local directory; tests
build a miniature ratings.dat in the same format.
"""

import os

import numpy as np


def read_data_sets(folder):
    """-> (N, 3) int32 array of [user_id, movie_id, rating]
    (same contract as the pyspark original's movielens.read_data_sets)."""
    path = os.path.join(folder, "ratings.dat")
    if not os.path.exists(path):
        raise FileNotFoundError(f"{path} not found (expected ml-1m layout)")
    rows = []
    with open(path, "r", encoding="latin-1") as f:
        for line in f:
            parts = line.rstrip("\n").split("::")
            if len(parts) < 3:
                continue
            rows.append((int(parts[0]), int(parts[1]), int(parts[2])))
    return np.asarray(rows, np.int32)


def get_id_pairs(folder):
    """-> (user, item) id pairs + ratings, 1-based ids preserved."""
    data = read_data_sets(folder)
    return data[:, :2], data[:, 2]
