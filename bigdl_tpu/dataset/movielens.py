"""MovieLens-1M ingestion (reference: pyspark/bigdl/dataset/movielens.py --
parses ml-1m/ratings.dat lines 'UserID::MovieID::Rating::Timestamp').

The loader parses the standard ml-1m layout from a local directory; tests
build a miniature ratings.dat in the same format.
"""

import os

import numpy as np


def read_data_sets(folder):
    """-> (N, 3) int32 array of [user_id, movie_id, rating]
    (same contract as the pyspark original's movielens.read_data_sets)."""
    path = os.path.join(folder, "ratings.dat")
    if not os.path.exists(path):
        raise FileNotFoundError(f"{path} not found (expected ml-1m layout)")
    rows = []
    with open(path, "r", encoding="latin-1") as f:
        for line in f:
            parts = line.rstrip("\n").split("::")
            if len(parts) < 3:
                continue
            rows.append((int(parts[0]), int(parts[1]), int(parts[2])))
    return np.asarray(rows, np.int32)


def get_id_pairs(folder):
    """-> (user, item) id pairs + ratings, 1-based ids preserved."""
    data = read_data_sets(folder)
    return data[:, :2], data[:, 2]


def write_ratings(folder, n_users=30, n_items=40, n=600, seed=0):
    """A miniature, deterministic ``ratings.dat`` in the ml-1m layout
    (the second-workload drill's dataset: the rating carries learnable
    user/item structure, so a few supervised steps visibly move the
    model).  Existing files are overwritten; returns the folder."""
    os.makedirs(folder, exist_ok=True)
    rng = np.random.default_rng(seed)
    users = rng.integers(1, n_users + 1, n)
    items = rng.integers(1, n_items + 1, n)
    # deterministic structure + a little noise: rating in 1..5
    ratings = ((users * 3 + items * 7) % 5) + 1
    flip = rng.random(n) < 0.05
    ratings = np.where(flip, rng.integers(1, 6, n), ratings)
    ts = 978300000 + np.arange(n)
    with open(os.path.join(folder, "ratings.dat"), "w") as f:
        for u, i, r, t in zip(users, items, ratings, ts):
            f.write(f"{u}::{i}::{r}::{t}\n")
    return folder


def to_id_features(pairs, n_users):
    """(user, item) 1-based id pairs -> dense ``(N, 2)`` float32 id
    features over ONE shared id space (items offset past the users):
    the input shape ``nn.sparse.sparse_recommender`` consumes
    (``DenseToSparse`` re-sparsifies inside the jitted step, so zero
    rows -- serving-bucket padding -- contribute nothing)."""
    pairs = np.asarray(pairs)
    return np.stack([pairs[:, 0], n_users + pairs[:, 1]],
                    axis=1).astype(np.float32)
