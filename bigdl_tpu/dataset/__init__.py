from bigdl_tpu.dataset.minibatch import (
    Sample, MiniBatch, SparseMiniBatch, PaddingParam, samples_to_minibatch,
)
from bigdl_tpu.dataset.transformer import (
    Transformer, ChainedTransformer, FnTransformer, SampleToMiniBatch,
    Normalizer,
)
from bigdl_tpu.dataset.dataset import (
    AbstractDataSet, LocalDataSet, TransformedDataSet, DistributedDataSet,
    array_dataset,
)
from bigdl_tpu.dataset import cifar, movielens, news20
from bigdl_tpu.dataset.image_folder import ImageFolderDataSet, image_folder
