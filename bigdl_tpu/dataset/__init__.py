from bigdl_tpu.dataset.minibatch import (
    Sample, MiniBatch, SparseMiniBatch, PaddingParam, samples_to_minibatch,
)
from bigdl_tpu.dataset.transformer import (
    Transformer, ChainedTransformer, FnTransformer, SampleToMiniBatch,
    Normalizer,
)
from bigdl_tpu.dataset.dataset import (
    AbstractDataSet, LocalDataSet, TransformedDataSet, DistributedDataSet,
    array_dataset,
)
from bigdl_tpu.dataset.prefetch import PrefetchDataSet
from bigdl_tpu.dataset.distributed import (
    ListPartitionSource, PartitionedDataSet, PartitionedSource, RDDSource,
    rdd_dataset)
from bigdl_tpu.dataset import cifar, movielens, news20
from bigdl_tpu.dataset.image_folder import ImageFolderDataSet, image_folder


class DataSet:
    """Factory namespace mirroring the reference's ``DataSet`` object
    (dataset/DataSet.scala:322 array, :420 ImageFolder, :482
    SeqFileFolder)."""

    @staticmethod
    def array(features, labels=None):
        return array_dataset(features, labels)

    @staticmethod
    def image_folder(path, size=None, **kw):
        """reference: DataSet.ImageFolder (DataSet.scala:420)."""
        return image_folder(path, size=size, **kw)

    @staticmethod
    def seq_file_folder(path, class_num=None):
        """reference: DataSet.SeqFileFolder.files (DataSet.scala:482) ->
        LocalDataSet of Samples with decoded images + 0-based labels."""
        import io

        import numpy as np

        from bigdl_tpu.dataset.minibatch import Sample
        from bigdl_tpu.dataset.seq_file import read_byte_records

        from PIL import Image

        records = read_byte_records(path, class_num=class_num)
        samples = []
        for img_bytes, label in records:
            img = np.asarray(
                Image.open(io.BytesIO(img_bytes)).convert("RGB"),
                np.float32) / 255.0
            samples.append(Sample(img, np.int32(label - 1)))
        return LocalDataSet(samples)

    @staticmethod
    def cifar10(folder, train=True):
        from bigdl_tpu.dataset.minibatch import Sample

        import numpy as np

        x, y = cifar.load_cifar10(folder, train=train)
        return LocalDataSet([Sample(xi, np.int32(yi))
                             for xi, yi in zip(x, y)])
