"""CLI Train/Test entry points for the model zoo.

The reference ships one scopt ``Train``/``Test`` main per model
(models/lenet/Train.scala:35, models/inception/Train.scala,
models/resnet/TrainCIFAR10.scala, models/autoencoder/Train.scala,
models/rnn/Train.scala); this is the argparse equivalent as subcommands:

    python -m bigdl_tpu.models.run lenet-train  -f <mnist-dir> -b 64
    python -m bigdl_tpu.models.run lenet-test   -f <mnist-dir> --model lenet.bigdl
    python -m bigdl_tpu.models.run vgg-train    -b 128 --dataset cifar-synth
    python -m bigdl_tpu.models.run resnet-train -b 128 --depth 20
    python -m bigdl_tpu.models.run autoencoder-train -f <mnist-dir>

When no data folder is given a deterministic synthetic dataset is used so
every main runs self-contained (the reference requires downloaded MNIST /
CIFAR; synthetic keeps the path exercisable in CI).
"""

import argparse
import os
import sys

import numpy as np


def _mnist(folder, n=2048):
    from bigdl_tpu.dataset import mnist
    if folder:
        base = os.path.join(folder, "train-images-idx3-ubyte")
        if os.path.exists(base) or os.path.exists(base + ".gz"):
            return (mnist.load_mnist(folder, train=True),
                    mnist.load_mnist(folder, train=False))
        print(f"[warn] no MNIST idx files under {folder}; "
              "falling back to synthetic data")
    x, y = mnist.synthetic_mnist(n)
    # held-out tail as the synthetic "test" split
    k = n - n // 4
    return (x[:k], y[:k]), (x[k:], y[k:])


def _synthetic_images(n, h, w, c, classes, seed=11):
    rng = np.random.default_rng(seed)
    y = rng.integers(0, classes, size=n)
    x = rng.normal(size=(n, h, w, c)).astype(np.float32)
    # class-dependent mean shift so accuracy can move off chance
    x += ((y[:, None, None, None] + 1) / classes).astype(np.float32)
    return x, y


def _to_dataset(x, y, batch):
    from bigdl_tpu.dataset import SampleToMiniBatch, array_dataset
    return array_dataset(x, y) >> SampleToMiniBatch(batch)


def _build_optimizer(args, model, train_ds, val_ds, criterion, method,
                     val_methods, strategy_kw=None):
    import bigdl_tpu.nn as nn  # noqa: F401  (registers layers for load)
    from bigdl_tpu.optim import Optimizer, Trigger
    from bigdl_tpu.utils.engine import Engine

    Engine.init()
    if getattr(args, "num_workers", 0):
        # async input pipeline (docs/performance.md, Input pipeline):
        # transform workers + bounded queue in front of the driver loop
        train_ds = train_ds.prefetch(num_workers=args.num_workers,
                                     queue_depth=args.queue_depth)
    route = strategy_kw or {"distributed": args.distributed}
    opt = Optimizer(model=model, dataset=train_ds, criterion=criterion,
                    optim_method=method, **route)
    opt.set_end_when(Trigger.max_epoch(args.max_epoch)
                     if args.max_iteration is None
                     else Trigger.max_iteration(args.max_iteration))
    if getattr(args, "sync_every", 1) != 1:
        opt.set_sync_every(args.sync_every)
    if val_ds is not None and val_methods:
        opt.set_validation(Trigger.every_epoch(), val_ds, val_methods)
    if args.checkpoint:
        opt.set_checkpoint(args.checkpoint, Trigger.every_epoch())
    if args.summary_dir:
        from bigdl_tpu.visualization import TrainSummary
        opt.set_train_summary(TrainSummary(args.summary_dir, args.app_name))
    return opt


def _common_flags(p, default_epochs=5):
    p.add_argument("-f", "--folder", default=None,
                   help="data folder (synthetic data when absent)")
    p.add_argument("-b", "--batchSize", type=int, default=64, dest="batch")
    p.add_argument("--learningRate", type=float, default=0.05, dest="lr")
    p.add_argument("--maxEpoch", type=int, default=default_epochs,
                   dest="max_epoch")
    p.add_argument("--maxIteration", type=int, default=None,
                   dest="max_iteration")
    p.add_argument("--checkpoint", default=None)
    p.add_argument("--summaryDir", default=None, dest="summary_dir")
    p.add_argument("--appName", default="bigdl_tpu", dest="app_name")
    p.add_argument("--distributed", action="store_true",
                   help="DistriOptimizer over the device mesh")
    p.add_argument("--model", default=None,
                   help="snapshot to load (resume / test)")
    p.add_argument("--synthN", type=int, default=2048, dest="synth_n")
    p.add_argument("--numWorkers", type=int, default=0, dest="num_workers",
                   help="prefetch transform workers (0 = synchronous)")
    p.add_argument("--queueDepth", type=int, default=4, dest="queue_depth",
                   help="prefetch queue depth (batches held ahead)")
    p.add_argument("--syncEvery", type=int, default=1, dest="sync_every",
                   help="block on the device loss every k-th step only")
    p.add_argument("--compilationCache", default=None,
                   dest="compilation_cache", metavar="DIR",
                   help="persistent XLA compilation cache dir: repeat "
                        "runs of the same program skip recompilation")


def cmd_lenet_train(args):
    import bigdl_tpu.nn as nn
    from bigdl_tpu import optim
    from bigdl_tpu.models.lenet import LeNet5
    from bigdl_tpu.utils import serializer

    (xtr, ytr), (xte, yte) = _mnist(args.folder, args.synth_n)
    model = serializer.load_module(args.model) if args.model else LeNet5()
    opt = _build_optimizer(
        args, model, _to_dataset(xtr, ytr, args.batch),
        _to_dataset(xte, yte, args.batch), nn.ClassNLLCriterion(),
        optim.SGD(learning_rate=args.lr, momentum=0.9, dampening=0.0),
        [optim.Top1Accuracy()])
    opt.optimize()
    if args.checkpoint:
        serializer.save_module(model, os.path.join(args.checkpoint, "lenet.bigdl"))


def cmd_lenet_test(args):
    from bigdl_tpu import optim
    from bigdl_tpu.models.lenet import LeNet5
    from bigdl_tpu.optim.local_optimizer import validate
    from bigdl_tpu.utils import serializer

    import jax

    _, (xte, yte) = _mnist(args.folder, args.synth_n)
    model = serializer.load_module(args.model) if args.model else LeNet5()
    model.build(jax.ShapeDtypeStruct(xte[: args.batch].shape, xte.dtype))
    results = validate(model, model.parameters()[0], model.state(),
                       _to_dataset(xte, yte, args.batch),
                       [optim.Top1Accuracy(), optim.Top5Accuracy()])
    for r in results:
        print(r)


def cmd_vgg_train(args):
    import bigdl_tpu.nn as nn
    from bigdl_tpu import optim
    from bigdl_tpu.models.vgg import VggForCifar10

    x, y = _synthetic_images(args.synth_n, 32, 32, 3, 10)
    holdout = max(1, min(256, len(x) // 4))
    model = VggForCifar10()
    opt = _build_optimizer(
        args, model, _to_dataset(x[:-holdout], y[:-holdout], args.batch),
        _to_dataset(x[-holdout:], y[-holdout:], args.batch), nn.ClassNLLCriterion(),
        optim.SGD(learning_rate=args.lr, momentum=0.9, dampening=0.0,
                  weight_decay=5e-4),
        [optim.Top1Accuracy()])
    opt.optimize()


def cmd_resnet_train(args):
    import bigdl_tpu.nn as nn
    from bigdl_tpu import optim
    from bigdl_tpu.models.resnet import ResNetCifar

    x, y = _synthetic_images(args.synth_n, 32, 32, 3, 10)
    holdout = max(1, min(256, len(x) // 4))
    model = ResNetCifar(depth=args.depth)
    opt = _build_optimizer(
        args, model, _to_dataset(x[:-holdout], y[:-holdout], args.batch),
        _to_dataset(x[-holdout:], y[-holdout:], args.batch),
        nn.CrossEntropyCriterion(),
        optim.SGD(learning_rate=args.lr, momentum=0.9, dampening=0.0,
                  weight_decay=1e-4, nesterov=True),
        [optim.Top1Accuracy()])
    opt.optimize()


def _validate_remat_policy(args):
    """Fail fast on an unknown --rematPolicy NAME -- before any data
    prep or device init, with the list of valid jax.checkpoint_policies
    names (nn.resolve_checkpoint_policy), instead of an opaque
    AttributeError at first apply."""
    policy = getattr(args, "remat_policy", None)
    if policy is not None:
        from bigdl_tpu.nn import resolve_checkpoint_policy
        resolve_checkpoint_policy(policy)
    return policy


def cmd_resnet_imagenet_train(args):
    """The published ResNet-50/ImageNet recipe (reference:
    models/resnet/README.md:131-149 + TrainImageNet.scala): global batch
    8192, 90 epochs, 5-epoch linear warmup 0.1 -> 3.2, then 0.1x decay at
    epochs 30/60/80, SGD momentum 0.9, weight decay 1e-4.  Data: a folder
    of Hadoop SequenceFiles (--folder, the reference's ImageNet prep) or an
    ImageFolder tree; synthetic stand-in otherwise (the recipe itself --
    schedule, batch, epochs -- is exactly the published one either way)."""
    import bigdl_tpu.nn as nn
    from bigdl_tpu import optim
    from bigdl_tpu.models.resnet import ResNet

    n_train = 1281167
    steps_per_epoch = max(int(np.ceil(n_train / args.batch)), 1)
    warmup_epochs = 5
    base_lr, max_lr = args.lr, args.max_lr
    warmup_iteration = steps_per_epoch * warmup_epochs
    delta = (max_lr - base_lr) / warmup_iteration

    if args.folder and any(f.endswith(".seq")
                           for f in os.listdir(args.folder)):
        import io

        from PIL import Image

        from bigdl_tpu.dataset.seq_file import read_byte_records

        recs = read_byte_records(args.folder, class_num=1000)
        x = np.stack([
            np.asarray(Image.open(io.BytesIO(b)).convert("RGB")
                       .resize((224, 224)), np.float32) / 255.0
            for b, _ in recs])
        y = np.asarray([int(l) - 1 for _, l in recs], np.int32)
        n_train = len(x)
        steps_per_epoch = max(int(np.ceil(n_train / args.batch)), 1)
        warmup_iteration = steps_per_epoch * warmup_epochs
        delta = (max_lr - base_lr) / max(warmup_iteration, 1)
    elif args.folder:
        from bigdl_tpu.dataset.image_folder import find_images, decode_image

        items, _ = find_images(args.folder)
        x = np.stack([decode_image(p, (224, 224)) for p, _ in items])
        y = np.asarray([label for _, label in items], np.int32)
    else:
        x, y = _synthetic_images(max(args.synth_n // 4, args.batch * 2),
                                 224, 224, 3, 1000)

    model = ResNet(depth=50, class_num=1000, remat=args.remat,
                   stem_s2d=args.s2d,
                   remat_policy=_validate_remat_policy(args))
    method = optim.SGD(
        learning_rate=base_lr, momentum=0.9, dampening=0.0,
        weight_decay=1e-4,
        learning_rate_schedule=optim.EpochDecayWithWarmUp(
            warmup_iteration, delta, steps_per_epoch))
    if args.fused:
        # one flat-vector parameter update kernel (docs/performance.md)
        method = optim.Fused(method)
    opt = _build_optimizer(
        args, model, _to_dataset(x, y, args.batch), None,
        nn.CrossEntropyCriterion(), method, [optim.Top1Accuracy()])
    opt.optimize()


def cmd_inception_train(args):
    import bigdl_tpu.nn as nn
    from bigdl_tpu import optim
    from bigdl_tpu.models.inception import (InceptionV1NoAuxClassifier,
                                            InceptionV2)

    x, y = _synthetic_images(max(args.synth_n // 8, args.batch * 2),
                             224, 224, 3, args.classes)
    model = (InceptionV2(args.classes) if args.version == "v2"
             else InceptionV1NoAuxClassifier(args.classes))
    opt = _build_optimizer(
        args, model, _to_dataset(x, y, args.batch), None,
        nn.ClassNLLCriterion(),
        optim.SGD(learning_rate=args.lr, momentum=0.9, dampening=0.0), [])
    opt.optimize()


def cmd_autoencoder_train(args):
    import bigdl_tpu.nn as nn
    from bigdl_tpu import optim
    from bigdl_tpu.dataset import SampleToMiniBatch, array_dataset
    from bigdl_tpu.models.rnn import Autoencoder

    (xtr, _), _ = _mnist(args.folder, args.synth_n)
    flat = xtr.reshape(len(xtr), -1)
    ds = array_dataset(xtr, flat) >> SampleToMiniBatch(args.batch)
    opt = _build_optimizer(args, Autoencoder(32), ds, None,
                           nn.MSECriterion(),
                           optim.Adam(learning_rate=args.lr), [])
    opt.optimize()


def cmd_rnn_train(args):
    import bigdl_tpu.nn as nn
    from bigdl_tpu import optim

    from bigdl_tpu.models.rnn import SimpleRNN

    rng = np.random.default_rng(3)
    vocab, seq = args.vocab, args.seq_len
    tokens = rng.integers(0, vocab, size=(args.synth_n, seq + 1))
    x, y = tokens[:, :-1], tokens[:, 1:]
    model = SimpleRNN(vocab, 40, vocab)
    opt = _build_optimizer(
        args, model, _to_dataset(x, y, args.batch), None,
        nn.TimeDistributedCriterion(nn.ClassNLLCriterion()),
        optim.SGD(learning_rate=args.lr), [])
    opt.optimize()


def cmd_transformer_train(args):
    """Transformer LM on a synthetic next-token corpus, single-device or
    sequence-parallel over a mesh (the long-context flagship; no reference
    analogue -- SURVEY.md §5 lists long-context as greenfield)."""
    import jax
    import jax.numpy as jnp

    import bigdl_tpu.nn as nn
    from bigdl_tpu import optim
    from bigdl_tpu.models.transformer import synthetic_corpus, transformer_lm

    vocab, seq = args.vocab, args.seq_len
    x, y = synthetic_corpus(args.synth_n, seq, vocab)
    remat_policy = _validate_remat_policy(args)
    #: --scanLayers auto|on|off -> None|True|False (transformer_lm's
    #: auto scans the deep configs; docs/performance.md)
    scan = {"auto": None, "on": True, "off": False}[args.scan_layers]
    # Pallas blockwise CE on TPU for big vocabs; plain formulation
    # elsewhere (ops/cross_entropy.py)
    crit = nn.TimeDistributedCriterion(nn.FusedSoftmaxCrossEntropyCriterion())

    if args.sp > 1 and args.pp > 1:
        raise ValueError("pick ONE of --sp / --pp (compose them in code "
                         "via parallel.pp_tp_shardings on a 3-D mesh)")
    if args.sp > 1 or args.pp > 1:
        if scan is True:
            raise ValueError(
                "--scanLayers on is incompatible with --sp/--pp: the "
                "model-parallel engines address per-block params "
                "(pp re-stacks blocks by STAGE); train scan-compiled "
                "models single-device or data-parallel")
        if args.pp > 1 and remat_policy is not None:
            # the pp engine re-implements the block forward per stage
            # (parallel/pp.py) and never runs TransformerLM.apply's
            # checkpoint wrapper -- silently accepting the flag would
            # "apply" a policy that changes nothing
            raise ValueError(
                "--rematPolicy has no effect under --pp: the pipeline "
                "engine drives the blocks directly and bypasses the "
                "model's remat wrapper; drop the flag (sp and "
                "single-device/dp paths honor it)")
        from bigdl_tpu.utils.engine import Engine

        from bigdl_tpu.models.transformer import CONFIGS

        deg = args.sp if args.sp > 1 else args.pp
        n_dev = jax.device_count()
        data_deg = n_dev // deg
        layers = CONFIGS[args.size][2]
        problems = []
        if n_dev % deg:
            problems.append(f"device count {n_dev} % degree {deg} != 0")
        if args.sp > 1 and seq % args.sp:
            problems.append(f"--seq-len {seq} % sp {args.sp} != 0")
        if args.pp > 1 and layers % args.pp:
            problems.append(f"--size {args.size} has {layers} "
                            f"blocks, not divisible into {args.pp} stages")
        if args.pp > 1 and args.batch % args.pp:
            problems.append(f"--batchSize {args.batch} % {args.pp} "
                            f"microbatches != 0")
        if (args.pp > 1 and args.batch % args.pp == 0
                and data_deg and (args.batch // args.pp) % data_deg):
            problems.append(f"microbatch {args.batch // args.pp} % "
                            f"data-parallel degree {data_deg} != 0")
        if data_deg and args.batch % data_deg:
            problems.append(f"--batchSize {args.batch} % data-parallel "
                            f"degree {data_deg} != 0")
        if problems:
            raise ValueError("model-parallel shape requirements: "
                             + "; ".join(problems))
        axis = "seq" if args.sp > 1 else "pipe"
        mesh = Engine.build_mesh((data_deg, deg), ("data", axis))
        model = transformer_lm(args.size, vocab, max_len=seq,
                               seq_axis_name="seq" if args.sp > 1 else None,
                               scan_layers=False,
                               remat_policy=remat_policy)
        strategy_kw = {"strategy": "sp" if args.sp > 1 else "pp",
                       "mesh": mesh}
        if args.pp > 1:
            strategy_kw.update(n_microbatches=args.pp,
                               schedule=args.pp_schedule)
        # full batches only: shard_map needs the batch axis divisible
        n_full = (len(x) // args.batch) * args.batch
        if n_full == 0:
            raise ValueError(f"--synthN {len(x)} < --batchSize {args.batch}")
        x, y = x[:n_full], y[:n_full]
        opt = _build_optimizer(args, model, _to_dataset(x, y, args.batch),
                               None, crit,
                               optim.Adam(learning_rate=args.lr), [],
                               strategy_kw=strategy_kw)
        opt.optimize()
        return

    model = transformer_lm(args.size, vocab, max_len=seq, scan_layers=scan,
                           remat_policy=remat_policy)
    opt = _build_optimizer(args, model, _to_dataset(x, y, args.batch), None,
                           crit, optim.Adam(learning_rate=args.lr), [])
    opt.optimize()


def _honor_env_platforms():
    from bigdl_tpu.utils.config import honor_env_platforms
    honor_env_platforms()


def main(argv=None):
    _honor_env_platforms()
    # progress must be visible out of the box (epoch/iteration/loss lines
    # come through logging.INFO); jax/XLA noise goes to bigdl.log via the
    # LoggerFilter analogue
    import logging

    from bigdl_tpu.utils.logger_filter import redirect_spark_info_logs
    logging.basicConfig(
        level=os.environ.get("BIGDL_LOG_LEVEL", "INFO").upper(),
        format="%(asctime)s %(levelname)-5s %(message)s")
    redirect_spark_info_logs()
    parser = argparse.ArgumentParser(prog="bigdl_tpu.models.run")
    sub = parser.add_subparsers(dest="command", required=True)

    specs = {
        "lenet-train": (cmd_lenet_train, 5, []),
        "lenet-test": (cmd_lenet_test, 1, []),
        "vgg-train": (cmd_vgg_train, 2, []),
        "resnet-train": (cmd_resnet_train, 2,
                         [("--depth", dict(type=int, default=20))]),
        "resnet-imagenet-train": (
            cmd_resnet_imagenet_train, 90,
            [("--maxLr", dict(type=float, default=3.2, dest="max_lr")),
             ("--fused", dict(action="store_true",
                              help="flat fused optimizer update")),
             ("--remat", dict(action="store_true",
                              help="rematerialise residual blocks")),
             ("--rematPolicy", dict(default=None, dest="remat_policy",
                                    metavar="NAME",
                                    help="jax.checkpoint_policies name for "
                                         "the block remat wrappers (e.g. "
                                         "dots_saveable, nothing_saveable; "
                                         "implies --remat)")),
             ("--s2d", dict(action="store_true",
                            help="space-to-depth 7x7 stem"))]),
        "inception-train": (cmd_inception_train, 1,
                            [("--version", dict(default="v1",
                                                choices=["v1", "v2"])),
                             ("--classes", dict(type=int, default=100))]),
        "autoencoder-train": (cmd_autoencoder_train, 2, []),
        "rnn-train": (cmd_rnn_train, 2,
                      [("--vocab", dict(type=int, default=100)),
                       ("--seq-len", dict(type=int, default=20,
                                          dest="seq_len"))]),
        "transformer-train": (
            cmd_transformer_train, 1,
            [("--vocab", dict(type=int, default=256)),
             ("--seq-len", dict(type=int, default=64, dest="seq_len")),
             ("--size", dict(default="tiny",
                             choices=["tiny", "small", "medium", "large"])),
             ("--sp", dict(type=int, default=1,
                           help="sequence-parallel degree (ring attention "
                                "over a data x seq mesh)")),
             ("--pp", dict(type=int, default=1,
                           help="pipeline-parallel stages (data x pipe "
                                "mesh; microbatches = stages)")),
             ("--pp-schedule", dict(default="gpipe",
                                    choices=["gpipe", "1f1b"],
                                    dest="pp_schedule")),
             ("--scanLayers", dict(default="auto",
                                   choices=["auto", "on", "off"],
                                   dest="scan_layers",
                                   help="compile the block stack as one "
                                        "lax.scan (auto: on for "
                                        "medium/large; incompatible with "
                                        "--sp/--pp)")),
             ("--rematPolicy", dict(default=None, dest="remat_policy",
                                    metavar="NAME",
                                    help="jax.checkpoint_policies name "
                                         "applied per transformer block "
                                         "(e.g. dots_saveable, "
                                         "nothing_saveable)"))]),
    }
    for name, (fn, epochs, extra) in specs.items():
        p = sub.add_parser(name)
        _common_flags(p, default_epochs=epochs)
        for flag, kw in extra:
            p.add_argument(flag, **kw)
        p.set_defaults(fn=fn)
        if name == "resnet-imagenet-train":
            # recipe defaults (models/resnet/README.md:131-149)
            p.set_defaults(lr=0.1)
        if name == "transformer-train":
            p.set_defaults(lr=1e-3)      # Adam-scale default

    args = parser.parse_args(argv)
    from bigdl_tpu.utils.config import (compilation_cache_note,
                                        enable_compilation_cache)
    # every invocation activates the cache (an explicit --compilationCache
    # DIR overrides the env/default path) and logs the warm/cold note, so
    # cache reuse across runs/legs is always visible; a telemetry-carrying
    # run additionally stamps the same status on its JSONL header
    enable_compilation_cache(getattr(args, "compilation_cache", None))
    logging.getLogger("bigdl_tpu").info(compilation_cache_note())
    args.fn(args)


if __name__ == "__main__":
    main(sys.argv[1:])
