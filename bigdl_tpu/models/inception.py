"""Inception v1 (GoogLeNet).

Reference: models/inception/Inception_v1.scala (Concat of 1x1 / 3x3 / 5x5 /
pool towers).  The tower fan-out uses Concat over the channel axis, exactly
the reference's structure; NHWC so the concat axis is -1.
"""

import bigdl_tpu.nn as nn


def _conv(n_in, n_out, k, stride=1, pad=0, name=None):
    return (nn.Sequential(name=name)
            .add(nn.SpatialConvolution(n_in, n_out, k, k, stride, stride,
                                       pad, pad, data_format="NHWC"))
            .add(nn.ReLU()))


def inception_module(n_in, c1, c3r, c3, c5r, c5, pool_proj):
    """One inception block (reference: Inception_v1.scala inception())."""
    concat = nn.Concat(3)
    concat.add(_conv(n_in, c1, 1))
    concat.add(nn.Sequential().add(_conv(n_in, c3r, 1))
               .add(_conv(c3r, c3, 3, 1, 1)))
    concat.add(nn.Sequential().add(_conv(n_in, c5r, 1))
               .add(_conv(c5r, c5, 5, 1, 2)))
    concat.add(nn.Sequential()
               .add(nn.SpatialMaxPooling(3, 3, 1, 1, 1, 1))
               .add(_conv(n_in, pool_proj, 1)))
    return concat


def InceptionV1NoAuxClassifier(class_num=1000):
    """Input (N, 224, 224, 3)
    (reference: Inception_v1_NoAuxClassifier.scala)."""
    return (
        nn.Sequential()
        .add(nn.SpatialConvolution(3, 64, 7, 7, 2, 2, 3, 3, data_format="NHWC"))
        .add(nn.ReLU())
        .add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
        .add(nn.SpatialCrossMapLRN(5, 0.0001, 0.75))
        .add(_conv(64, 64, 1))
        .add(_conv(64, 192, 3, 1, 1))
        .add(nn.SpatialCrossMapLRN(5, 0.0001, 0.75))
        .add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
        .add(inception_module(192, 64, 96, 128, 16, 32, 32))
        .add(inception_module(256, 128, 128, 192, 32, 96, 64))
        .add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
        .add(inception_module(480, 192, 96, 208, 16, 48, 64))
        .add(inception_module(512, 160, 112, 224, 24, 64, 64))
        .add(inception_module(512, 128, 128, 256, 24, 64, 64))
        .add(inception_module(512, 112, 144, 288, 32, 64, 64))
        .add(inception_module(528, 256, 160, 320, 32, 128, 128))
        .add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
        .add(inception_module(832, 256, 160, 320, 32, 128, 128))
        .add(inception_module(832, 384, 192, 384, 48, 128, 128))
        .add(nn.GlobalAveragePooling2D())
        .add(nn.Dropout(0.4))
        .add(nn.Linear(1024, class_num))
        .add(nn.LogSoftMax())
    )
