"""Inception v1 (GoogLeNet).

Reference: models/inception/Inception_v1.scala (Concat of 1x1 / 3x3 / 5x5 /
pool towers).  The tower fan-out uses Concat over the channel axis, exactly
the reference's structure; NHWC so the concat axis is -1.
"""

import bigdl_tpu.nn as nn


def _conv(n_in, n_out, k, stride=1, pad=0, name=None):
    return (nn.Sequential(name=name)
            .add(nn.SpatialConvolution(n_in, n_out, k, k, stride, stride,
                                       pad, pad, data_format="NHWC"))
            .add(nn.ReLU()))


def inception_module(n_in, c1, c3r, c3, c5r, c5, pool_proj):
    """One inception block (reference: Inception_v1.scala inception())."""
    concat = nn.Concat(3)
    concat.add(_conv(n_in, c1, 1))
    concat.add(nn.Sequential().add(_conv(n_in, c3r, 1))
               .add(_conv(c3r, c3, 3, 1, 1)))
    concat.add(nn.Sequential().add(_conv(n_in, c5r, 1))
               .add(_conv(c5r, c5, 5, 1, 2)))
    concat.add(nn.Sequential()
               .add(nn.SpatialMaxPooling(3, 3, 1, 1, 1, 1))
               .add(_conv(n_in, pool_proj, 1)))
    return concat


def _conv_bn(n_in, n_out, k, stride=1, pad=0):
    """conv + BN + ReLU unit (reference: Inception_v2.scala conv/bn/sc/relu
    triples)."""
    return (nn.Sequential()
            .add(nn.SpatialConvolution(n_in, n_out, k, k, stride, stride,
                                       pad, pad, data_format="NHWC"))
            .add(nn.SpatialBatchNormalization(n_out, 1e-3))
            .add(nn.ReLU()))


def inception_layer_v2(n_in, c1, c3, c3xx, pool_spec):
    """One Inception-v2 (BN-Inception) block.

    Reference: Inception_v2.scala ``Inception_Layer_v2.apply`` — four towers:
    optional 1x1 (c1=0 drops it), 3x3 (stride 2 when the pool tower is a
    stride-2 max pool with no projection), double-3x3, and a pool tower
    (``("avg"|"max", proj)``; proj=0 means stride-2 pass-through, no conv).
    """
    pool_kind, pool_proj = pool_spec
    downsample = pool_kind == "max" and pool_proj == 0
    concat = nn.Concat(3)
    if c1 != 0:
        concat.add(_conv_bn(n_in, c1, 1))
    c3r, c3o = c3
    tower3 = nn.Sequential().add(_conv_bn(n_in, c3r, 1))
    tower3.add(_conv_bn(c3r, c3o, 3, 2 if downsample else 1, 1))
    concat.add(tower3)
    cxr, cxo = c3xx
    towerx = (nn.Sequential()
              .add(_conv_bn(n_in, cxr, 1))
              .add(_conv_bn(cxr, cxo, 3, 1, 1))
              .add(_conv_bn(cxo, cxo, 3, 2 if downsample else 1, 1)))
    concat.add(towerx)
    pool = nn.Sequential()
    if pool_kind == "max":
        if pool_proj != 0:
            pool.add(nn.SpatialMaxPooling(3, 3, 1, 1, 1, 1).ceil())
        else:
            pool.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    else:
        pool.add(nn.SpatialAveragePooling(3, 3, 1, 1, 1, 1).ceil())
    if pool_proj != 0:
        pool.add(_conv_bn(n_in, pool_proj, 1))
    concat.add(pool)
    return concat


def InceptionV2(class_num=1000):
    """BN-Inception (Inception v2), input (N, 224, 224, 3).

    Reference: Inception_v2.scala ``Inception_v2_NoAuxClassifier.apply``
    (:186-227; the aux-classifier variant differs only in training heads).
    """
    return (
        nn.Sequential()
        .add(nn.SpatialConvolution(3, 64, 7, 7, 2, 2, 3, 3,
                                   data_format="NHWC", name="conv1/7x7_s2"))
        .add(nn.SpatialBatchNormalization(64, 1e-3))
        .add(nn.ReLU())
        .add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
        .add(_conv_bn(64, 64, 1))
        .add(_conv_bn(64, 192, 3, 1, 1))
        .add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
        .add(inception_layer_v2(192, 64, (64, 64), (64, 96), ("avg", 32)))
        .add(inception_layer_v2(256, 64, (64, 96), (64, 96), ("avg", 64)))
        .add(inception_layer_v2(320, 0, (128, 160), (64, 96), ("max", 0)))
        .add(inception_layer_v2(576, 224, (64, 96), (96, 128), ("avg", 128)))
        .add(inception_layer_v2(576, 192, (96, 128), (96, 128), ("avg", 128)))
        .add(inception_layer_v2(576, 160, (128, 160), (128, 160), ("avg", 96)))
        .add(inception_layer_v2(576, 96, (128, 192), (160, 192), ("avg", 96)))
        .add(inception_layer_v2(576, 0, (128, 192), (192, 256), ("max", 0)))
        .add(inception_layer_v2(1024, 352, (192, 320), (160, 224), ("avg", 128)))
        .add(inception_layer_v2(1024, 352, (192, 320), (192, 224), ("max", 128)))
        .add(nn.GlobalAveragePooling2D())
        .add(nn.Linear(1024, class_num, name="loss3/classifier"))
        .add(nn.LogSoftMax())
    )


def _v1_feature1():
    """Stem through inception_4a (shared by both v1 builders;
    reference Inception_v1.scala feature1)."""
    return (
        nn.Sequential()
        .add(nn.SpatialConvolution(3, 64, 7, 7, 2, 2, 3, 3, data_format="NHWC"))
        .add(nn.ReLU())
        .add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
        .add(nn.SpatialCrossMapLRN(5, 0.0001, 0.75))
        .add(_conv(64, 64, 1))
        .add(_conv(64, 192, 3, 1, 1))
        .add(nn.SpatialCrossMapLRN(5, 0.0001, 0.75))
        .add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
        .add(inception_module(192, 64, 96, 128, 16, 32, 32))
        .add(inception_module(256, 128, 128, 192, 32, 96, 64))
        .add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
        .add(inception_module(480, 192, 96, 208, 16, 48, 64)))


def _v1_feature2():
    """inception_4b..4d (shared; reference feature2)."""
    return (
        nn.Sequential()
        .add(inception_module(512, 160, 112, 224, 24, 64, 64))
        .add(inception_module(512, 128, 128, 256, 24, 64, 64))
        .add(inception_module(512, 112, 144, 288, 32, 64, 64)))


def _v1_tail():
    """inception_4e..5b + global pool (shared; reference output3 head)."""
    return (
        nn.Sequential()
        .add(inception_module(528, 256, 160, 320, 32, 128, 128))
        .add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
        .add(inception_module(832, 256, 160, 320, 32, 128, 128))
        .add(inception_module(832, 384, 192, 384, 48, 128, 128))
        .add(nn.GlobalAveragePooling2D()))


def InceptionV1NoAuxClassifier(class_num=1000, has_dropout=True):
    """Input (N, 224, 224, 3)
    (reference: Inception_v1_NoAuxClassifier.scala)."""
    model = nn.Sequential().add(_v1_feature1()).add(_v1_feature2()) \
        .add(_v1_tail())
    if has_dropout:
        model.add(nn.Dropout(0.4))
    return model.add(nn.Linear(1024, class_num)).add(nn.LogSoftMax())


def InceptionV1(class_num=1000, has_dropout=True):
    """GoogLeNet WITH the two auxiliary training heads (reference:
    Inception_v1.scala:190-280): the three LogSoftMax classifier outputs
    concatenate along the class axis -> (N, 3 * class_num), ordered
    [main, aux2, aux1] exactly like the reference's nested Concat(2)
    (split2 = [output3, output2]; split1 = [mainBranch, output1]).
    Serving slices the first ``class_num`` columns (the main head).
    """
    def aux_head(n_in, name):
        head = (nn.Sequential(name=name)
                .add(nn.SpatialAveragePooling(5, 5, 3, 3).ceil())
                .add(_conv(n_in, 128, 1))
                .add(nn.Flatten())
                .add(nn.Linear(128 * 4 * 4, 1024))
                .add(nn.ReLU()))
        if has_dropout:
            head.add(nn.Dropout(0.7))
        return head.add(nn.Linear(1024, class_num)).add(nn.LogSoftMax())

    feature1 = _v1_feature1()
    feature2 = _v1_feature2()

    output3 = _v1_tail()
    if has_dropout:
        output3.add(nn.Dropout(0.4))
    output3.add(nn.Linear(1024, class_num)).add(nn.LogSoftMax())

    split2 = nn.Concat(1).add(output3).add(aux_head(528, "loss2"))
    main_branch = nn.Sequential().add(feature2).add(split2)
    split1 = nn.Concat(1).add(main_branch).add(aux_head(512, "loss1"))
    return nn.Sequential().add(feature1).add(split1)
