"""Transformer LM model family: the long-context flagship.

No reference analogue exists (BigDL 0.8 predates transformers —
SURVEY.md §5 'Long-context / sequence parallelism: absent; greenfield');
this family is the north-star capability built on the same machinery the
reference families use (models/*/Train.scala CLI style), with optional
sequence parallelism over a device mesh (ppermute ring attention or
Ulysses all-to-all — parallel/{ring_attention,ulysses}.py).
"""

from typing import Optional

import numpy as np

from bigdl_tpu.nn.attention import TransformerLM


#: size -> (hidden, heads, layers); the single source of truth the CLI's
#: pipeline-stage validation reads too
CONFIGS = {
    "tiny":  (256,   4,    4),
    "small": (768,  12,   12),
    "medium": (1024, 16,  24),
    "large": (1536, 16,   36),
}


def transformer_lm(size: str = "tiny", vocab_size: int = 32000,
                   max_len: int = 2048,
                   seq_axis_name: Optional[str] = None,
                   seq_mode: str = "ring",
                   scan_layers: Optional[bool] = None,
                   remat_policy: Optional[str] = None) -> TransformerLM:
    """Named configs; 'tiny'/'small' fit a chip's HBM comfortably, larger
    sizes pair with tp/pp/sp shardings.

    ``scan_layers=None`` (the default) is AUTO: the deep configs
    (``medium``/``large``) compile their blocks as one ``lax.scan``
    (nn.ScanLayers -- ~layer-count-fold lower jit-compile time,
    docs/performance.md "Step-time campaign"), the shallow ones stay
    unrolled; pass True/False to force.  ``remat_policy`` names a
    ``jax.checkpoint_policies`` entry applied per block during training
    (``"nothing_saveable"``/``"dots_saveable"``/None)."""
    if size not in CONFIGS:
        raise ValueError(f"unknown size {size!r}; pick from {list(CONFIGS)}")
    hidden, heads, layers = CONFIGS[size]
    if scan_layers is None:
        # auto: deep configs scan; sequence-parallel models stay unrolled
        # (the pp engine additionally re-stacks blocks by stage and is
        # routed with an explicit scan_layers=False by the CLI)
        scan_layers = size in ("medium", "large") and seq_axis_name is None
    return TransformerLM(vocab_size, hidden, heads, layers, max_len=max_len,
                         seq_axis_name=seq_axis_name, seq_mode=seq_mode,
                         scan_layers=scan_layers, remat_policy=remat_policy)


def synthetic_corpus(n_seq: int, seq_len: int, vocab_size: int, seed=0):
    """Next-token-prediction pairs from a Markov-ish synthetic stream (so a
    converging loss is meaningful, unlike uniform noise)."""
    rng = np.random.default_rng(seed)
    # each token depends on the previous one: learnable structure
    trans = rng.integers(0, vocab_size, size=(vocab_size, 4))
    toks = np.empty((n_seq, seq_len + 1), np.int32)
    toks[:, 0] = rng.integers(0, vocab_size, n_seq)
    choice = rng.integers(0, 4, size=(n_seq, seq_len))
    for t in range(seq_len):
        toks[:, t + 1] = trans[toks[:, t], choice[:, t]]
    return toks[:, :-1], toks[:, 1:]
