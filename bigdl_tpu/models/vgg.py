"""VGG-16/19 + CIFAR variant.

Reference: models/vgg/Vgg_16.scala, Vgg_19.scala, VggForCifar10.scala.
NHWC layout.
"""

import bigdl_tpu.nn as nn

_CFG = {
    16: [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
         512, 512, 512, "M", 512, 512, 512, "M"],
    19: [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
         512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}


def _features(cfg, batch_norm=False):
    seq = nn.Sequential()
    n_in = 3
    for v in cfg:
        if v == "M":
            seq.add(nn.SpatialMaxPooling(2, 2, 2, 2))
        else:
            seq.add(nn.SpatialConvolution(n_in, v, 3, 3, 1, 1, 1, 1,
                                          data_format="NHWC"))
            if batch_norm:
                seq.add(nn.SpatialBatchNormalization(v))
            seq.add(nn.ReLU())
            n_in = v
    return seq


def Vgg16(class_num=1000, batch_norm=False):
    """Input (N, 224, 224, 3) (reference: models/vgg/Vgg_16.scala)."""
    return (
        _features(_CFG[16], batch_norm)
        .add(nn.Reshape((512 * 7 * 7,)))
        .add(nn.Linear(512 * 7 * 7, 4096)).add(nn.ReLU()).add(nn.Dropout(0.5))
        .add(nn.Linear(4096, 4096)).add(nn.ReLU()).add(nn.Dropout(0.5))
        .add(nn.Linear(4096, class_num))
    )


def Vgg19(class_num=1000, batch_norm=False):
    return (
        _features(_CFG[19], batch_norm)
        .add(nn.Reshape((512 * 7 * 7,)))
        .add(nn.Linear(512 * 7 * 7, 4096)).add(nn.ReLU()).add(nn.Dropout(0.5))
        .add(nn.Linear(4096, 4096)).add(nn.ReLU()).add(nn.Dropout(0.5))
        .add(nn.Linear(4096, class_num))
    )


def VggForCifar10(class_num=10):
    """Input (N, 32, 32, 3) (reference: models/vgg/VggForCifar10.scala --
    conv+BN stacks then 512-unit classifier)."""
    def block(n_in, n_out):
        return (nn.SpatialConvolution(n_in, n_out, 3, 3, 1, 1, 1, 1,
                                      data_format="NHWC"),
                nn.SpatialBatchNormalization(n_out), nn.ReLU())

    seq = nn.Sequential()
    n_in = 3
    for v in [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
              512, 512, 512, "M", 512, 512, 512, "M"]:
        if v == "M":
            seq.add(nn.SpatialMaxPooling(2, 2, 2, 2))
        else:
            for m in block(n_in, v):
                seq.add(m)
            n_in = v
    return (seq.add(nn.Reshape((512,)))
            .add(nn.Linear(512, 512)).add(nn.BatchNormalization(512))
            .add(nn.ReLU()).add(nn.Dropout(0.5))
            .add(nn.Linear(512, class_num)).add(nn.LogSoftMax()))
