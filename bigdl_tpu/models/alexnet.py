"""AlexNet.

Reference: example/loadmodel/AlexNet.scala — two variants:
``AlexNet`` (original Krizhevsky net: LRN + grouped convolutions, groups=2
on conv2/4/5) and ``AlexNet_OWT`` ("one weird trick" variant: no LRN, no
groups).  TPU-native: NHWC layout, conv via lax.conv_general_dilated with
``feature_group_count`` for the grouped convs (maps straight onto the MXU —
no im2col, no split/concat emulation of groups).
"""

import bigdl_tpu.nn as nn


def _flatten_classifier(model, class_num, has_dropout):
    model.add(nn.Flatten())
    model.add(nn.Linear(256 * 6 * 6, 4096, name="fc6"))
    model.add(nn.ReLU())
    if has_dropout:
        model.add(nn.Dropout(0.5))
    model.add(nn.Linear(4096, 4096, name="fc7"))
    model.add(nn.ReLU())
    if has_dropout:
        model.add(nn.Dropout(0.5))
    model.add(nn.Linear(4096, class_num, name="fc8"))
    model.add(nn.LogSoftMax())
    return model


def AlexNet(class_num=1000, has_dropout=True):
    """Original AlexNet, input (N, 227, 227, 3).

    Reference: example/loadmodel/AlexNet.scala ``object AlexNet`` (grouped
    conv2/conv4/conv5, LRN after conv1/conv2).
    """
    model = (
        nn.Sequential()
        .add(nn.SpatialConvolution(3, 96, 11, 11, 4, 4, 0, 0, name="conv1"))
        .add(nn.ReLU())
        .add(nn.SpatialCrossMapLRN(5, 0.0001, 0.75))
        .add(nn.SpatialMaxPooling(3, 3, 2, 2))
        .add(nn.SpatialConvolution(96, 256, 5, 5, 1, 1, 2, 2, n_group=2,
                                   name="conv2"))
        .add(nn.ReLU())
        .add(nn.SpatialCrossMapLRN(5, 0.0001, 0.75))
        .add(nn.SpatialMaxPooling(3, 3, 2, 2))
        .add(nn.SpatialConvolution(256, 384, 3, 3, 1, 1, 1, 1, name="conv3"))
        .add(nn.ReLU())
        .add(nn.SpatialConvolution(384, 384, 3, 3, 1, 1, 1, 1, n_group=2,
                                   name="conv4"))
        .add(nn.ReLU())
        .add(nn.SpatialConvolution(384, 256, 3, 3, 1, 1, 1, 1, n_group=2,
                                   name="conv5"))
        .add(nn.ReLU())
        .add(nn.SpatialMaxPooling(3, 3, 2, 2))
    )
    return _flatten_classifier(model, class_num, has_dropout)


def AlexNetOWT(class_num=1000, has_dropout=True):
    """"One weird trick" AlexNet, input (N, 224, 224, 3).

    Reference: example/loadmodel/AlexNet.scala ``object AlexNet_OWT``
    (no LRN, no conv groups).
    """
    model = (
        nn.Sequential()
        .add(nn.SpatialConvolution(3, 64, 11, 11, 4, 4, 2, 2, name="conv1"))
        .add(nn.ReLU())
        .add(nn.SpatialMaxPooling(3, 3, 2, 2))
        .add(nn.SpatialConvolution(64, 192, 5, 5, 1, 1, 2, 2, name="conv2"))
        .add(nn.ReLU())
        .add(nn.SpatialMaxPooling(3, 3, 2, 2))
        .add(nn.SpatialConvolution(192, 384, 3, 3, 1, 1, 1, 1, name="conv3"))
        .add(nn.ReLU())
        .add(nn.SpatialConvolution(384, 256, 3, 3, 1, 1, 1, 1, name="conv4"))
        .add(nn.ReLU())
        .add(nn.SpatialConvolution(256, 256, 3, 3, 1, 1, 1, 1, name="conv5"))
        .add(nn.ReLU())
        .add(nn.SpatialMaxPooling(3, 3, 2, 2))
    )
    return _flatten_classifier(model, class_num, has_dropout)
