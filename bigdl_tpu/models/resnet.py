"""ResNet (reference: models/resnet/ResNet.scala -- cifar and imagenet
variants with basic/bottleneck blocks built from Sequential/ConcatTable/
CAddTable).

NHWC end-to-end (TPU-preferred; SURVEY.md section 7: convert at the model
boundary, never per-op).  The residual add is CAddTable over a ConcatTable,
structurally matching the reference.
"""

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.initialization import MsraFiller, Zeros


def _conv(n_in, n_out, k, stride=1, pad=0):
    return nn.SpatialConvolution(
        n_in, n_out, k, k, stride, stride, pad, pad, with_bias=False,
        data_format="NHWC", weight_init=MsraFiller(False))


def _bn(n):
    return nn.SpatialBatchNormalization(n)


def _shortcut(n_in, n_out, stride):
    if n_in != n_out or stride != 1:
        return nn.Sequential().add(_conv(n_in, n_out, 1, stride)).add(_bn(n_out))
    return nn.Identity()


def basic_block(n_in, n_out, stride=1):
    """3x3 + 3x3 residual block (reference: ResNet.scala basicBlock)."""
    main = (nn.Sequential()
            .add(_conv(n_in, n_out, 3, stride, 1)).add(_bn(n_out)).add(nn.ReLU())
            .add(_conv(n_out, n_out, 3, 1, 1)).add(_bn(n_out)))
    return (nn.Sequential()
            .add(nn.ConcatTable().add(main).add(_shortcut(n_in, n_out, stride)))
            .add(nn.CAddTable())
            .add(nn.ReLU()))


def bottleneck(n_in, planes, stride=1, expansion=4):
    """1x1 -> 3x3 -> 1x1 block (reference: ResNet.scala bottleneck)."""
    n_out = planes * expansion
    main = (nn.Sequential()
            .add(_conv(n_in, planes, 1)).add(_bn(planes)).add(nn.ReLU())
            .add(_conv(planes, planes, 3, stride, 1)).add(_bn(planes)).add(nn.ReLU())
            .add(_conv(planes, n_out, 1)).add(_bn(n_out)))
    return (nn.Sequential()
            .add(nn.ConcatTable().add(main).add(_shortcut(n_in, n_out, stride)))
            .add(nn.CAddTable())
            .add(nn.ReLU()))


_IMAGENET_CFG = {
    18: ("basic", [2, 2, 2, 2]),
    34: ("basic", [3, 4, 6, 3]),
    50: ("bottleneck", [3, 4, 6, 3]),
    101: ("bottleneck", [3, 4, 23, 3]),
    152: ("bottleneck", [3, 8, 36, 3]),
}


def ResNet(depth=50, class_num=1000, remat=False, stem_s2d=False,
           remat_policy=None):
    """ImageNet ResNet; input (N, 224, 224, 3)
    (reference: ResNet.scala apply with DatasetType.ImageNet).

    ``remat=True`` wraps every residual block in ``nn.Remat``: the train
    step recomputes block activations during backward instead of storing
    them -- a bandwidth-for-FLOPs trade for the HBM-bound TPU step
    (docs/performance.md).  ``remat_policy`` names a
    ``jax.checkpoint_policies`` entry forwarded to those wrappers
    (``"dots_saveable"`` keeps matmul/conv outputs, ``"nothing_saveable"``
    recomputes everything; None = save block inputs only) and implies
    ``remat=True``; unknown names fail at construction with the valid
    list.  ``stem_s2d=True`` computes the 7x7/s2 stem via
    ``nn.SpaceToDepthStem`` (identical weights, MXU-friendlier shape).
    All options are numerically equivalent to the plain model
    (tests test_models.py / test_conv.py)."""
    kind, layout = _IMAGENET_CFG[depth]
    remat = remat or remat_policy is not None
    wrap = ((lambda m: nn.Remat(m, policy=remat_policy)) if remat
            else (lambda m: m))
    stem_cls = ((lambda: nn.SpaceToDepthStem(
                    3, 64, 7, data_format="NHWC",
                    weight_init=MsraFiller(False)))
                if stem_s2d else
                (lambda: nn.SpatialConvolution(
                    3, 64, 7, 7, 2, 2, 3, 3, with_bias=False,
                    data_format="NHWC", weight_init=MsraFiller(False))))
    model = (nn.Sequential()
             .add(stem_cls())
             .add(_bn(64)).add(nn.ReLU())
             .add(nn.SpatialMaxPooling(3, 3, 2, 2, 1, 1)))
    n_in = 64
    planes = [64, 128, 256, 512]
    for stage, (p, count) in enumerate(zip(planes, layout)):
        for i in range(count):
            stride = 2 if (stage > 0 and i == 0) else 1
            if kind == "basic":
                model.add(wrap(basic_block(n_in, p, stride)))
                n_in = p
            else:
                model.add(wrap(bottleneck(n_in, p, stride)))
                n_in = p * 4
    model.add(nn.GlobalAveragePooling2D())
    model.add(nn.Linear(n_in, class_num))
    return model


def ResNetCifar(depth=20, class_num=10):
    """CIFAR ResNet: 6n+2 layers (reference: ResNet.scala DatasetType.CIFAR10)."""
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    model = (nn.Sequential()
             .add(_conv(3, 16, 3, 1, 1)).add(_bn(16)).add(nn.ReLU()))
    n_in = 16
    for stage, p in enumerate([16, 32, 64]):
        for i in range(n):
            stride = 2 if (stage > 0 and i == 0) else 1
            model.add(basic_block(n_in, p, stride))
            n_in = p
    model.add(nn.GlobalAveragePooling2D())
    model.add(nn.Linear(64, class_num))
    return model
