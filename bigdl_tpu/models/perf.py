"""Synthetic-data throughput drivers.

Reference: models/utils/LocalOptimizerPerf.scala,
models/utils/DistriOptimizerPerf.scala:82 and nn/mkldnn/Perf.scala:56-126 —
log imgs/sec (or iters/sec) on synthetic data for the standard models.

    python -m bigdl_tpu.models.perf --model resnet50 -b 32 -i 20
    python -m bigdl_tpu.models.perf --model vgg16 --distributed

Unlike the reference (threads x replica fwd/bwd), the measured unit here is
the fused jitted train step (fwd + bwd + update in one XLA program); the
first iteration is excluded as compile time.
"""

import argparse
import time

import numpy as np


# image models: (module, ctor, input shape, classes)
MODELS = {
    "lenet": ("bigdl_tpu.models.lenet", "LeNet5", (28, 28, 1), 10),
    "alexnet": ("bigdl_tpu.models.alexnet", "AlexNetOWT", (224, 224, 3), 1000),
    "vgg16": ("bigdl_tpu.models.vgg", "Vgg16", (224, 224, 3), 1000),
    "vgg19": ("bigdl_tpu.models.vgg", "Vgg19", (224, 224, 3), 1000),
    "resnet50": ("bigdl_tpu.models.resnet", "ResNet", (224, 224, 3), 1000),
    "inception_v1": ("bigdl_tpu.models.inception",
                     "InceptionV1NoAuxClassifier", (224, 224, 3), 1000),
    "inception_v2": ("bigdl_tpu.models.inception", "InceptionV2",
                     (224, 224, 3), 1000),
}


# token models (the BASELINE.md "SimpleRNN LM sample throughput" row and
# the transformer flagship): (module, ctor, ctor args/kwargs, vocab, seq_len)
TOKEN_MODELS = {
    "simplernn": ("bigdl_tpu.models.rnn", "SimpleRNN",
                  (4000, 40, 4000), {}, 4000, 25),
    "lstm_lm": ("bigdl_tpu.models.rnn", "LSTMLanguageModel",
                (10000, 128, 256), {}, 10000, 35),
    "transformer": ("bigdl_tpu.nn.attention", "TransformerLM",
                    (8000, 256, 4, 4), {"max_len": 256}, 8000, 256),
}


def _resolve(mod_name, fn_name):
    import importlib
    return getattr(importlib.import_module(mod_name), fn_name)


def build_model(name):
    mod_name, fn_name, shape, classes = MODELS[name]
    return _resolve(mod_name, fn_name)(), shape, classes


def build_token_model(name):
    mod_name, fn_name, args, kwargs, vocab, seq_len = TOKEN_MODELS[name]
    return _resolve(mod_name, fn_name)(*args, **kwargs), vocab, seq_len


def run_perf(model_name="resnet50", batch=32, iterations=20,
             distributed=False, fused=False):
    import jax
    import jax.numpy as jnp

    import bigdl_tpu.nn as nn
    from bigdl_tpu import optim
    from bigdl_tpu.optim.train_step import make_train_step

    rng = np.random.default_rng(0)
    if model_name in TOKEN_MODELS:
        if distributed:
            raise NotImplementedError(
                "--distributed drives the image-model DistriOptimizer "
                "path; token models run the single-chip fused step")
        # LM perf (reference: models/rnn/README.md throughput log + the
        # transformer flagship): (N, T) tokens -> per-token NLL
        model, vocab, seq_len = build_token_model(model_name)
        x = jnp.asarray(rng.integers(0, vocab, size=(batch, seq_len)),
                        jnp.int32)
        target = jnp.asarray(rng.integers(0, vocab, size=(batch, seq_len)))
        if model_name == "transformer":
            # TimeDistributed flattens (N,T,V)->(N*T,V), which is the
            # shape that engages the Pallas fused-CE kernel
            criterion = nn.TimeDistributedCriterion(
                nn.FusedSoftmaxCrossEntropyCriterion())
        else:
            criterion = nn.TimeDistributedCriterion(nn.ClassNLLCriterion())
    else:
        model, shape, classes = build_model(model_name)
        x = jnp.asarray(rng.normal(size=(batch,) + shape), jnp.float32)
        target = jnp.asarray(rng.integers(0, classes, size=batch))
        criterion = nn.ClassNLLCriterion()
    method = optim.SGD(learning_rate=0.01)
    if fused:
        # one flat-vector update kernel (docs/performance.md op accounting)
        method = optim.Fused(method)

    if distributed:
        # DistriOptimizerPerf equivalent: run the sharded DistriOptimizer
        # loop on synthetic data and report its per-iteration throughput.
        from bigdl_tpu.dataset import SampleToMiniBatch, array_dataset
        from bigdl_tpu.optim import DistriOptimizer, Trigger

        n = batch * 4
        xs = np.asarray(rng.normal(size=(n,) + shape), np.float32)
        ys = rng.integers(0, classes, size=n)
        ds = array_dataset(xs, ys) >> SampleToMiniBatch(batch)
        opt = DistriOptimizer(model, ds, criterion, method)
        opt.set_end_when(Trigger.max_iteration(iterations))
        t0 = time.perf_counter()
        opt.optimize()
        dt = time.perf_counter() - t0
        rate = batch * iterations / dt
        print(f"[{model_name}] distributed batch {batch}: "
              f"{rate:.1f} records/sec incl. compile")
        return rate

    model.build(jax.ShapeDtypeStruct(x.shape, x.dtype))
    params, mstate = model.parameters()[0], model.state()
    opt_state = method.init_state(params)
    step = jax.jit(make_train_step(model, criterion, method),
                   donate_argnums=(0, 1, 2))

    key = jax.random.key(0)
    # compile (excluded)
    params, mstate, opt_state, loss = step(params, mstate, opt_state, x,
                                           target, key)
    jax.block_until_ready(loss)

    times = []
    for i in range(iterations):
        t0 = time.perf_counter()
        params, mstate, opt_state, loss = step(params, mstate, opt_state, x,
                                               target, jax.random.fold_in(key, i))
        jax.block_until_ready(loss)
        times.append(time.perf_counter() - t0)
        print(f"iter {i + 1}/{iterations}: "
              f"{batch / times[-1]:.1f} records/sec, loss {float(loss):.4f}")

    med = float(np.median(times))
    print(f"[{model_name}] batch {batch}: median {batch / med:.1f} records/sec "
          f"({med * 1e3:.1f} ms/iter)")
    return batch / med


def _honor_env_platforms():
    from bigdl_tpu.utils.config import honor_env_platforms
    honor_env_platforms()


def main(argv=None):
    _honor_env_platforms()
    p = argparse.ArgumentParser(prog="bigdl_tpu.models.perf")
    p.add_argument("--model", default="resnet50",
                   choices=sorted(MODELS) + sorted(TOKEN_MODELS))
    p.add_argument("-b", "--batchSize", type=int, default=32, dest="batch")
    p.add_argument("-i", "--iteration", type=int, default=20,
                   dest="iterations")
    p.add_argument("--distributed", action="store_true")
    p.add_argument("--fused", action="store_true",
                   help="flat fused optimizer update (optim.Fused)")
    args = p.parse_args(argv)
    run_perf(args.model, args.batch, args.iterations, args.distributed,
             fused=args.fused)


if __name__ == "__main__":
    main()
