"""LeNet-5 (reference: models/lenet/LeNet5.scala).

Built NHWC (TPU-preferred layout); input (N, 28, 28, 1).
"""

import bigdl_tpu.nn as nn


def LeNet5(class_num: int = 10) -> nn.Sequential:
    return (
        nn.Sequential()
        .add(nn.Reshape((28, 28, 1)))
        .add(nn.SpatialConvolution(1, 6, 5, 5, name="conv1_5x5"))
        .add(nn.Tanh())
        .add(nn.SpatialMaxPooling(2, 2, 2, 2))
        .add(nn.SpatialConvolution(6, 12, 5, 5, name="conv2_5x5"))
        .add(nn.Tanh())
        .add(nn.SpatialMaxPooling(2, 2, 2, 2))
        .add(nn.Reshape((12 * 4 * 4,)))
        .add(nn.Linear(12 * 4 * 4, 100, name="fc1"))
        .add(nn.Tanh())
        .add(nn.Linear(100, class_num, name="fc2"))
        .add(nn.LogSoftMax())
    )


def LeNet5Graph(class_num: int = 10) -> "nn.Graph":
    """Graph-API variant (reference: LeNet5.scala graph())."""
    inp = nn.Input()
    x = nn.Reshape((28, 28, 1))(inp)
    x = nn.SpatialConvolution(1, 6, 5, 5)(x)
    x = nn.Tanh()(x)
    x = nn.SpatialMaxPooling(2, 2, 2, 2)(x)
    x = nn.SpatialConvolution(6, 12, 5, 5)(x)
    x = nn.Tanh()(x)
    x = nn.SpatialMaxPooling(2, 2, 2, 2)(x)
    x = nn.Reshape((12 * 4 * 4,))(x)
    x = nn.Linear(12 * 4 * 4, 100)(x)
    x = nn.Tanh()(x)
    x = nn.Linear(100, class_num)(x)
    out = nn.LogSoftMax()(x)
    return nn.Graph([inp], [out])
