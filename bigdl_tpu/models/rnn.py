"""SimpleRNN language model + Autoencoder.

Reference: models/rnn/SimpleRNN.scala (LookupTable-free one-hot LM:
Recurrent(RnnCell) + TimeDistributed(Linear)), models/autoencoder/
Autoencoder.scala (784 -> 32 -> 784 MLP).
"""

import bigdl_tpu.nn as nn


def SimpleRNN(input_size, hidden_size, output_size):
    """(N, T) int tokens -> (N, T, output_size) log-probs
    (reference: models/rnn/SimpleRNN.scala)."""
    return (
        nn.Sequential()
        .add(nn.LookupTable(input_size, hidden_size))
        .add(nn.Recurrent(nn.RnnCell(hidden_size, hidden_size)))
        .add(nn.TimeDistributed(nn.Linear(hidden_size, output_size)))
        .add(nn.LogSoftMax())
    )


def LSTMLanguageModel(vocab_size, embed_size, hidden_size):
    """PTB-style LSTM LM (reference: example/languagemodel PTBModel)."""
    return (
        nn.Sequential()
        .add(nn.LookupTable(vocab_size, embed_size))
        .add(nn.Recurrent(nn.LSTM(embed_size, hidden_size)))
        .add(nn.Recurrent(nn.LSTM(hidden_size, hidden_size)))
        .add(nn.TimeDistributed(nn.Linear(hidden_size, vocab_size)))
        .add(nn.LogSoftMax())
    )


def Autoencoder(class_num=32):
    """784 -> 32 -> 784 (reference: models/autoencoder/Autoencoder.scala)."""
    return (
        nn.Sequential()
        .add(nn.Reshape((784,)))
        .add(nn.Linear(784, class_num))
        .add(nn.ReLU())
        .add(nn.Linear(class_num, 784))
        .add(nn.Sigmoid())
    )
