"""Training visualization (reference: visualization/TrainSummary.scala:32,
ValidationSummary.scala; hooked by the optimizers per trigger at
optim/AbstractOptimizer.scala:47-91)."""

import os

from bigdl_tpu.visualization.tensorboard import FileWriter, read_scalar


class Summary:
    def __init__(self, log_dir: str, app_name: str, sub_dir: str):
        self.log_dir = os.path.join(log_dir, app_name, sub_dir)
        self.writer = FileWriter(self.log_dir)

    def add_scalar(self, tag: str, value: float, step: int):
        self.writer.add_scalar(tag, value, step)
        return self

    def add_histogram(self, tag: str, values, step: int):
        self.writer.add_histogram(tag, values, step)
        return self

    def read_scalar(self, tag: str):
        """-> [(step, value, wall_time)] (reference: TrainSummary.readScalar)."""
        return read_scalar(self.log_dir, tag)

    def close(self):
        self.writer.close()


class TrainSummary(Summary):
    """Reference: visualization/TrainSummary.scala:32."""

    def __init__(self, log_dir: str, app_name: str):
        super().__init__(log_dir, app_name, "train")
        self._triggers = {}

    def set_summary_trigger(self, name: str, trigger):
        """Enable 'Parameters'/'Gradients' histograms per trigger
        (reference: TrainSummary.setSummaryTrigger)."""
        self._triggers[name] = trigger
        return self

    def add_step_event(self, event):
        """Write the per-step scalars from ONE telemetry step event
        (the same dict the observability JSONL records), so TensorBoard
        and telemetry.jsonl can never disagree on loss/throughput
        (docs/observability.md)."""
        step = event["step"]
        self.add_scalar("Loss", event["loss"], step)
        self.add_scalar("Throughput", event["records_per_s"], step)
        if "data_wait_s" in event:
            self.add_scalar("DataWaitSeconds", event["data_wait_s"], step)
        return self

    def add_health_event(self, event):
        """Write the numerics scalars from ONE ``kind: "health"``
        telemetry event (the sampled on-device stats --
        observability/health.py): run-level ``Health/*`` plus per-layer
        ``Health/GradNorm<path>`` / ``Health/UpdateRatio<path>``.  Same
        single-source-of-truth contract as ``add_step_event``."""
        step = event["step"]
        self.add_scalar("Health/GradNorm", event["grad_norm"], step)
        self.add_scalar("Health/UpdateRatioMax",
                        event["update_ratio_max"], step)
        self.add_scalar("Health/NonFiniteGrads",
                        event["nonfinite_grads"], step)
        self.add_scalar("Health/NonFiniteParams",
                        event["nonfinite_params"], step)
        if "ef_residual_norm" in event:
            # gradient-compression error-feedback residual (the
            # docs/performance.md "watch for growth" signal)
            self.add_scalar("Health/EfResidualNorm",
                            event["ef_residual_norm"], step)
        for name, rec in (event.get("layers") or {}).items():
            self.add_scalar("Health/GradNorm" + name,
                            rec["grad_norm"], step)
            self.add_scalar("Health/UpdateRatio" + name,
                            rec["update_ratio"], step)
        return self

    def get_summary_trigger(self, name: str):
        return self._triggers.get(name)


class ValidationSummary(Summary):
    """Reference: visualization/ValidationSummary.scala."""

    def __init__(self, log_dir: str, app_name: str):
        super().__init__(log_dir, app_name, "validation")
