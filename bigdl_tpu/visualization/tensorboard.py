"""Minimal TensorBoard event-file writer, no TF dependency.

Reference: visualization/tensorboard/{FileWriter,EventWriter,RecordWriter}.scala
+ netty/Crc32c.java -- the reference likewise writes TFRecord-framed Event
protos by hand.  Here the Event/Summary protos are hand-encoded (they are
tiny and stable: tags 1/2/3 wall_time/step/summary; Summary.Value tag/simple_value),
and the TFRecord framing uses the masked crc32c TensorFlow requires.
"""

import os
import struct
import threading
import time
import zlib


# --------------------------------------------------------------------------- #
# crc32c (Castagnoli) -- table-driven, matching netty/Crc32c.java.
# --------------------------------------------------------------------------- #

_CRC_TABLE = []


def _build_table():
    poly = 0x82F63B78
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        _CRC_TABLE.append(c)


_build_table()


def crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


# --------------------------------------------------------------------------- #
# Tiny protobuf encoder (only what Event/Summary need).
# --------------------------------------------------------------------------- #


def _varint(n: int) -> bytes:
    out = b""
    while True:
        b7 = n & 0x7F
        n >>= 7
        if n:
            out += bytes([b7 | 0x80])
        else:
            out += bytes([b7])
            return out


def _tag(field: int, wire: int) -> bytes:
    return _varint(field << 3 | wire)


def _double_field(field: int, value: float) -> bytes:
    return _tag(field, 1) + struct.pack("<d", value)


def _float_field(field: int, value: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", value)


def _int64_field(field: int, value: int) -> bytes:
    return _tag(field, 0) + _varint(value & 0xFFFFFFFFFFFFFFFF)


def _bytes_field(field: int, data: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(data)) + data


def encode_scalar_event(tag: str, value: float, step: int,
                        wall_time: float) -> bytes:
    # Summary.Value: 1=tag, 2=simple_value
    sval = _bytes_field(1, tag.encode()) + _float_field(2, float(value))
    summary = _bytes_field(1, sval)  # Summary: repeated Value value = 1
    # Event: 1=wall_time(double), 2=step(int64), 5=summary
    return (_double_field(1, wall_time) + _int64_field(2, int(step))
            + _bytes_field(5, summary))


def encode_histogram_event(tag: str, values, step: int,
                           wall_time: float) -> bytes:
    """HistogramProto: 1=min 2=max 3=num 4=sum 5=sum_squares
    6=bucket_limit(packed double) 7=bucket(packed double)."""
    import numpy as np

    v = np.asarray(values, np.float64).reshape(-1)
    counts, edges = np.histogram(v, bins=30)
    hist = (_double_field(1, float(v.min())) + _double_field(2, float(v.max()))
            + _double_field(3, float(v.size)) + _double_field(4, float(v.sum()))
            + _double_field(5, float((v * v).sum())))
    limits = b"".join(struct.pack("<d", e) for e in edges[1:])
    buckets = b"".join(struct.pack("<d", float(c)) for c in counts)
    hist += _bytes_field(6, limits) + _bytes_field(7, buckets)
    sval = _bytes_field(1, tag.encode()) + _bytes_field(4, hist)  # 4=histo
    summary = _bytes_field(1, sval)
    return (_double_field(1, wall_time) + _int64_field(2, int(step))
            + _bytes_field(5, summary))


class FileWriter:
    """TFRecord-framed event writer
    (reference: visualization/tensorboard/FileWriter.scala:31)."""

    def __init__(self, log_dir: str):
        os.makedirs(log_dir, exist_ok=True)
        fname = f"events.out.tfevents.{int(time.time())}.bigdl_tpu"
        self.path = os.path.join(log_dir, fname)
        self._f = open(self.path, "ab")
        self._lock = threading.Lock()
        # file-version header event
        version = (_double_field(1, time.time())
                   + _bytes_field(3, b"brain.Event:2"))
        self._write_record(version)

    def _write_record(self, payload: bytes):
        header = struct.pack("<Q", len(payload))
        with self._lock:
            self._f.write(header)
            self._f.write(struct.pack("<I", _masked_crc(header)))
            self._f.write(payload)
            self._f.write(struct.pack("<I", _masked_crc(payload)))
            self._f.flush()

    def add_scalar(self, tag: str, value: float, step: int):
        self._write_record(
            encode_scalar_event(tag, value, step, time.time()))

    def add_histogram(self, tag: str, values, step: int):
        self._write_record(
            encode_histogram_event(tag, values, step, time.time()))

    def close(self):
        self._f.close()


# --------------------------------------------------------------------------- #
# Read-back (reference: visualization readScalar for notebooks).
# --------------------------------------------------------------------------- #


def read_scalar(log_dir: str, tag: str):
    """-> list of (step, value, wall_time) for a tag, across event files."""
    out = []
    for fname in sorted(os.listdir(log_dir)):
        if "tfevents" not in fname:
            continue
        with open(os.path.join(log_dir, fname), "rb") as f:
            data = f.read()
        off = 0
        while off + 12 <= len(data):
            (length,) = struct.unpack_from("<Q", data, off)
            off += 12  # len + len_crc
            payload = data[off:off + length]
            off += length + 4  # payload + payload_crc
            out.extend(_parse_event_scalar(payload, tag))
    return out


def _read_varint(data, off):
    shift = n = 0
    while True:
        b = data[off]
        off += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, off
        shift += 7


def _parse_fields(data):
    off = 0
    while off < len(data):
        key, off = _read_varint(data, off)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, off = _read_varint(data, off)
        elif wire == 1:
            val = data[off:off + 8]
            off += 8
        elif wire == 2:
            ln, off = _read_varint(data, off)
            val = data[off:off + ln]
            off += ln
        elif wire == 5:
            val = data[off:off + 4]
            off += 4
        else:
            return
        yield field, wire, val


def _parse_event_scalar(payload, want_tag):
    wall = step = None
    results = []
    for field, wire, val in _parse_fields(payload):
        if field == 1 and wire == 1:
            wall = struct.unpack("<d", val)[0]
        elif field == 2 and wire == 0:
            step = val
        elif field == 5 and wire == 2:  # summary
            for f2, w2, v2 in _parse_fields(val):
                if f2 == 1 and w2 == 2:  # Summary.Value
                    tag = None
                    simple = None
                    for f3, w3, v3 in _parse_fields(v2):
                        if f3 == 1 and w3 == 2:
                            tag = v3.decode()
                        elif f3 == 2 and w3 == 5:
                            simple = struct.unpack("<f", v3)[0]
                    if tag == want_tag and simple is not None:
                        results.append((step or 0, simple, wall or 0.0))
    return results
