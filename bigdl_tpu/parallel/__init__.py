from bigdl_tpu.parallel.zero import FlatParamSpace
