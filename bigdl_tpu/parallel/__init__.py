from bigdl_tpu.parallel.zero import FlatParamSpace
from bigdl_tpu.parallel.reshard import (LayoutSpec, redistribute,
                                        read_snapshot_layout,
                                        to_model_layout)
