"""Pipeline parallelism (GPipe schedule) over a ``pipe`` mesh axis.

No reference analogue (SURVEY.md section 2.4: pipeline parallelism absent) --
built the canonical TPU way: transformer blocks are split into ``n_stages``
contiguous stages whose parameters are *stacked* on a leading stage dimension
and sharded over the ``pipe`` mesh axis.  Inside ``shard_map`` every device
runs its own stage; activations move stage->stage with a single
``lax.ppermute`` hop per schedule tick (nearest-neighbour on the ICI ring,
the cheapest collective there is).  The schedule is the classic GPipe loop:
``n_micro + n_stages - 1`` ticks, each device computing every tick (bubble
ticks compute garbage that is masked out), microbatch *t* entering stage 0 at
tick *t* and leaving the last stage at tick ``t + n_stages - 1``.

Autodiff runs straight through the schedule: the transpose of ``ppermute`` is
the reverse-ring ``ppermute``, so ``jax.grad`` of the shard_map'd loss *is*
the 1F1B-ish backward pipeline -- no hand-written backward schedule.

Embedding and the LM head are computed replicated (they are cheap relative
to the blocks); only the block stack is pipelined.  Composes with data
parallelism via a 2-D ``(data, pipe)`` mesh: the batch is sharded over
``data`` and shard_map's transpose machinery inserts the gradient psums.
"""

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from bigdl_tpu.nn.module import child_rng
from bigdl_tpu.utils.compat import shard_map


def stack_stage_params(model, n_stages: int):
    """Split a built TransformerLM's blocks into ``n_stages`` stacked stages.

    -> dict with
       ``embed``:  {wte, wpe}                       (replicated)
       ``stages``: {layer{j}: block-params-stacked} (leading dim = stage)
       ``tail``:   {ln_f, head}                     (replicated)
    """
    params = model._params
    n_layers = len(model.blocks)
    assert n_layers % n_stages == 0, (n_layers, n_stages)
    lps = n_layers // n_stages
    stages = {}
    for j in range(lps):
        per_stage = [params[f"block{s * lps + j}"] for s in range(n_stages)]
        stages[f"layer{j}"] = jax.tree.map(
            lambda *xs: jnp.stack(xs), *per_stage)
    return {
        "embed": {"wte": params["wte"], "wpe": params["wpe"]},
        "stages": stages,
        "tail": {"ln_f": params["ln_f"], "head": params["head"]},
    }


def unstack_stage_params(model, pp_params):
    """Inverse of stack_stage_params -> plain TransformerLM params dict."""
    out = {"wte": pp_params["embed"]["wte"], "wpe": pp_params["embed"]["wpe"],
           "ln_f": pp_params["tail"]["ln_f"],
           "head": pp_params["tail"]["head"]}
    stages = pp_params["stages"]
    lps = len(stages)
    n_stages = jax.tree.leaves(stages["layer0"])[0].shape[0]
    for s in range(n_stages):
        for j in range(lps):
            out[f"block{s * lps + j}"] = jax.tree.map(
                lambda a: a[s], stages[f"layer{j}"])
    return out


def pp_shardings(pp_params, mesh, pipe_axis="pipe"):
    """NamedShardings: stage-stacked leaves sharded on dim 0, rest replicated."""
    rep = NamedSharding(mesh, P())
    staged = NamedSharding(mesh, P(pipe_axis))
    return {
        "embed": jax.tree.map(lambda _: rep, pp_params["embed"]),
        "stages": jax.tree.map(lambda _: staged, pp_params["stages"]),
        "tail": jax.tree.map(lambda _: rep, pp_params["tail"]),
    }


def pp_tp_shardings(pp_params, mesh, pipe_axis="pipe", model_axis="model",
                    rules=None):
    """3-D composition shardings: stage-stacked leaves sharded over
    ``pipe`` on dim 0 AND Megatron-style over ``model`` on their weight
    dims (TRANSFORMER_TP_RULES shifted by the stage dimension); embed/tail
    replicated.  Use with make_pp_train_step(..., manual_axes=
    ("data", "pipe")) so the model axis stays automatic (GSPMD)."""
    import re

    from jax.tree_util import keystr, tree_flatten_with_path, tree_unflatten

    from bigdl_tpu.parallel.tp import TRANSFORMER_TP_RULES

    rules = rules if rules is not None else TRANSFORMER_TP_RULES
    rep = NamedSharding(mesh, P())

    def stage_shardings(tree):
        leaves, treedef = tree_flatten_with_path(tree)
        out = []
        for path, leaf in leaves:
            name = keystr(path)
            spec = [pipe_axis] + [None] * (leaf.ndim - 1)
            for pattern, dims in rules:
                if re.search(pattern, name):
                    if len(dims) == leaf.ndim - 1:
                        spec = [pipe_axis] + [
                            d if d is None else model_axis for d in dims]
                    break
            out.append(NamedSharding(mesh, P(*spec)))
        return tree_unflatten(treedef, out)

    return {
        "embed": jax.tree.map(lambda _: rep, pp_params["embed"]),
        "stages": stage_shardings(pp_params["stages"]),
        "tail": jax.tree.map(lambda _: rep, pp_params["tail"]),
    }


def make_pp_loss_fn(model, criterion, mesh, n_microbatches: int,
                    pipe_axis: str = "pipe",
                    data_axis: Optional[str] = None,
                    manual_axes: Optional[tuple] = None,
                    compute_dtype=None):
    """-> loss(pp_params, x_tokens, y_tokens) with the GPipe schedule inside.

    ``x``/``y``: int32 (batch, T); batch must divide n_microbatches (times
    the data-axis size when present).

    ``manual_axes``: mesh axes handled manually by this shard_map; axes NOT
    listed (e.g. a ``model`` tensor-parallel axis on a 3-D mesh) stay
    automatic -- GSPMD partitions the per-stage math over them from the
    argument shardings (pp_tp_shardings).  Default: all mesh axes manual
    (the 2-D data x pipe case).
    """
    n_stages = mesh.shape[pipe_axis]
    lps = len(model.blocks) // n_stages

    def stage_fn(stage_params, x, rng):
        for j in range(lps):
            x, _ = model.blocks[0].apply(
                stage_params[f"layer{j}"], (), x, training=True,
                rng=child_rng(rng, j))
        return x

    def per_device(pp_params, x, y, rng):
        # x, y: (n_micro, mb_local, T) on this device
        from bigdl_tpu.optim.train_step import _cast_params
        cdt = compute_dtype or jnp.float32
        stage = lax.axis_index(pipe_axis)
        # slice the stage dim off BEFORE the compute-dtype cast, so the
        # rank>=2 rule sees the true per-leaf ranks (a stacked bias is
        # (n_stages, C) -- rank 2 -- but is still a VPU vector operand
        # that must stay an fp32 master)
        sp = _cast_params(jax.tree.map(lambda a: a[0],
                                       pp_params["stages"]), compute_dtype)
        emb = _cast_params(pp_params["embed"], compute_dtype)
        tailp = _cast_params(pp_params["tail"], compute_dtype)
        n_micro, mb, t = x.shape

        def embed(tok):
            h = jnp.take(emb["wte"], tok, axis=0)
            return h + emb["wpe"][:t][None]

        d = emb["wte"].shape[1]
        fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, tk):
            recv, outs = carry
            mb_idx = jnp.clip(tk, 0, n_micro - 1)
            inp = jnp.where(stage == 0, embed(x[mb_idx]), recv)
            out = stage_fn(sp, inp, child_rng(child_rng(rng, 7), tk))
            out_idx = tk - (n_stages - 1)
            valid = (stage == n_stages - 1) & (out_idx >= 0)
            widx = jnp.clip(out_idx, 0, n_micro - 1)
            outs = outs.at[widx].set(jnp.where(valid, out, outs[widx]))
            send = lax.ppermute(out, pipe_axis, fwd_perm)
            return (send, outs), None

        init = (jnp.zeros((mb, t, d), cdt),
                jnp.zeros((n_micro, mb, t, d), cdt))
        (_, outs), _ = lax.scan(tick, init,
                                jnp.arange(n_micro + n_stages - 1))
        # replicated tail on the collected last-stage activations
        h = outs.reshape(n_micro * mb, t, d)
        h, _ = model.ln_f.apply(tailp["ln_f"], (), h)
        logits = h @ tailp["head"].astype(h.dtype).T
        loss_local = criterion.apply(logits.astype(jnp.float32),
                                     y.reshape(n_micro * mb, t))
        loss = lax.psum(
            jnp.where(stage == n_stages - 1, loss_local, 0.0), pipe_axis)
        if data_axis is not None:
            loss = lax.pmean(loss, data_axis)
        return loss

    batch_spec = P(None, data_axis) if data_axis else P()
    smap_kwargs = {}
    if manual_axes is not None:
        smap_kwargs["axis_names"] = frozenset(manual_axes)
    smapped = shard_map(
        per_device, mesh=mesh,
        in_specs=({"embed": P(), "stages": P(pipe_axis), "tail": P()},
                  batch_spec, batch_spec, P()),
        out_specs=P(),
        check_vma=False,
        **smap_kwargs,
    )

    def loss_fn(pp_params, x, y, rng=None):
        n, t = x.shape
        assert n % n_microbatches == 0, (n, n_microbatches)
        if data_axis is not None:
            mb = n // n_microbatches
            assert mb % mesh.shape[data_axis] == 0, (
                f"microbatch size {mb} must divide over the "
                f"'{data_axis}' axis ({mesh.shape[data_axis]} devices)")
        xm = x.reshape(n_microbatches, n // n_microbatches, t)
        ym = y.reshape(n_microbatches, n // n_microbatches, t)
        if rng is None:
            rng = jax.random.key(0)
        return smapped(pp_params, xm, ym, rng)

    return loss_fn


def make_pp_train_step(model, criterion, optim_method, mesh,
                       n_microbatches: int, pipe_axis: str = "pipe",
                       data_axis: Optional[str] = None,
                       manual_axes: Optional[tuple] = None,
                       compute_dtype=None):
    """-> jitted step(pp_params, opt_state, x, y, rng) -> (params', opt', loss).

    Stage-stacked params (and their optimizer moments) live sharded over the
    ``pipe`` axis; the update runs where the shard lives (optimizer-state
    parallelism, the pipeline analogue of the reference's chunk ownership in
    parameters/AllReduceParameter.scala:84).  ``manual_axes``: see
    make_pp_loss_fn -- pass ("data", "pipe") on a 3-D data x pipe x model
    mesh to compose with GSPMD tensor parallelism.
    """
    from bigdl_tpu.nn.module import has_frozen
    if has_frozen(model):
        raise NotImplementedError(
            "freeze() is honored by make_train_step and the "
            "DistriOptimizer flat-chunk step; this model-parallel engine "
            "does not mask frozen parameters yet -- unfreeze() before "
            "building, or train with LocalOptimizer/DistriOptimizer")
    loss_fn = make_pp_loss_fn(model, criterion, mesh, n_microbatches,
                              pipe_axis, data_axis, manual_axes,
                              compute_dtype)

    def step(pp_params, opt_state, x, y, rng):
        loss, grads = jax.value_and_grad(loss_fn)(pp_params, x, y, rng)
        new_params, new_opt = optim_method.update(grads, opt_state, pp_params)
        return new_params, new_opt, loss

    return jax.jit(step, donate_argnums=(0, 1))


def make_pp_1f1b_train_step(model, criterion, optim_method, mesh,
                            n_microbatches: int, pipe_axis: str = "pipe",
                            data_axis: Optional[str] = None,
                            manual_axes: Optional[tuple] = None,
                            compute_dtype=None):
    """GPipe-equivalent gradients with the 1F1B (PipeDream-flush) schedule
    and a BOUNDED activation stash.

    The GPipe path (make_pp_train_step) differentiates straight through
    its scan, so autodiff stashes one residual set per tick -- memory
    grows with ``n_microbatches``.  Here the schedule is hand-written in
    ONE scan of ``M + 2S - 1`` ticks: device ``d`` runs the forward of
    microbatch ``t - d`` and the backward of microbatch ``t - (2S-1-d)``
    in the same tick (one-forward-one-backward steady state).  Backward
    uses per-stage ``jax.vjp`` with the stage INPUT rematerialised from a
    ring stash of ``2S`` slots -- the in-flight window of the 1F1B
    schedule -- so activation memory is O(S), independent of M.  Weights
    update once at the flush, so gradients are numerically the GPipe/
    single-device gradients (asserted in tests), not the PipeDream
    weight-stashing approximation.

    Activations ride the forward ring (+1 ppermute) and gradients the
    reverse ring (-1 ppermute), one hop each per tick -- both
    nearest-neighbour on the ICI.

    Same model scope as make_pp_loss_fn: a built TransformerLM with
    stage-stacked block params (embed/tail replicated).
    """
    n_stages = mesh.shape[pipe_axis]
    lps = len(model.blocks) // n_stages
    M = n_microbatches
    S = n_stages
    W = 2 * S                     # stash slots >= max residual lifetime 2S-1

    def stage_fn(stage_params, x, rng):
        for j in range(lps):
            x, _ = model.blocks[0].apply(
                stage_params[f"layer{j}"], (), x, training=True,
                rng=child_rng(rng, j))
        return x

    def per_device(pp_params, x, y, rng):
        # x, y: (M, mb, T) int tokens on this device's data shard
        from bigdl_tpu.optim.train_step import _cast_params
        cdt = compute_dtype or jnp.float32
        stage = lax.axis_index(pipe_axis)
        # slice the stage dim BEFORE the cast so the rank>=2 rule sees
        # true per-leaf ranks (stacked biases stay fp32 masters)
        sp = _cast_params(jax.tree.map(lambda a: a[0],
                                       pp_params["stages"]), compute_dtype)
        emb = _cast_params(pp_params["embed"], compute_dtype)
        tail = _cast_params(pp_params["tail"], compute_dtype)
        n_micro, mb, t = x.shape
        d_model = emb["wte"].shape[1]
        fwd_perm = [(i, (i + 1) % S) for i in range(S)]
        bwd_perm = [(i, (i - 1) % S) for i in range(S)]

        def embed_fn(e, tok):
            h = jnp.take(e["wte"], tok, axis=0)
            return h + e["wpe"][:t][None]

        def tail_loss(tl, h, tok_y):
            hn, _ = model.ln_f.apply(tl["ln_f"], (), h)
            logits = hn @ tl["head"].astype(hn.dtype).T
            # mean over this microbatch; the flush divides by M so the
            # total equals the criterion's full-batch mean
            return criterion.apply(logits.astype(jnp.float32), tok_y)

        def mrng(m):
            # keyed like the GPipe path's forward tick tk = m + stage
            # (make_pp_loss_fn), so (a) each stage draws distinct dropout
            # masks and (b) 1F1B gradients equal GPipe's under dropout;
            # the backward recompute reuses the same key by construction
            return child_rng(child_rng(rng, 7), m + stage)

        # fp32 gradient accumulators shaped like the UNCAST master params
        # (the per-tick vjp cotangents arrive in the compute dtype and are
        # upcast on accumulation -- the same master-grad semantics the
        # GPipe path gets from differentiating through its cast)
        zeros_g = {
            "embed": jax.tree.map(jnp.zeros_like, pp_params["embed"]),
            "stages": jax.tree.map(
                lambda a: jnp.zeros_like(a[0]), pp_params["stages"]),
            "tail": jax.tree.map(jnp.zeros_like, pp_params["tail"]),
        }

        def tick(carry, tk):
            fwd_recv, bwd_recv, stash, seeds, gacc, loss_acc = carry

            # ---- forward leg: microbatch mf = tk - stage ------------- #
            mf = tk - stage
            mf_ok = (mf >= 0) & (mf < M)
            mf_i = jnp.clip(mf, 0, M - 1)
            fwd_in = jnp.where(stage == 0,
                               embed_fn(emb, x[mf_i]), fwd_recv)
            out = stage_fn(sp, fwd_in, mrng(mf_i))
            stash = stash.at[mf_i % W].set(
                jnp.where(mf_ok, fwd_in, stash[mf_i % W]))

            # last stage: loss + seed gradient + tail grads via one vjp
            def tail_both(tl, h):
                return tail_loss(tl, h, y[mf_i])
            loss_m, tail_vjp = jax.vjp(tail_both, tail, out)
            dtail_m, seed_m = tail_vjp(jnp.ones((), jnp.float32))
            is_last = stage == S - 1
            take_loss = mf_ok & is_last
            loss_acc = loss_acc + jnp.where(take_loss, loss_m, 0.0)
            gacc = dict(gacc)
            gacc["tail"] = jax.tree.map(
                lambda a, g: a + jnp.where(take_loss, g, 0.0).astype(a.dtype),
                gacc["tail"], dtail_m)
            seeds = seeds.at[mf_i % 2].set(
                jnp.where(take_loss, seed_m, seeds[mf_i % 2]))

            # ---- backward leg: microbatch mbk = tk - (2S-1-stage) ---- #
            mbk = tk - (2 * S - 1 - stage)
            mb_ok = (mbk >= 0) & (mbk < M)
            mb_i = jnp.clip(mbk, 0, M - 1)
            xin = stash[mb_i % W]
            gin = jnp.where(stage == S - 1, seeds[mb_i % 2], bwd_recv)

            def stage_both(p, xi):
                return stage_fn(p, xi, mrng(mb_i))
            _, stage_vjp = jax.vjp(stage_both, sp, xin)
            dsp, dx = stage_vjp(gin)
            gacc["stages"] = jax.tree.map(
                lambda a, g: a + jnp.where(mb_ok, g, 0.0).astype(a.dtype),
                gacc["stages"], dsp)

            # stage 0 consumes dx into the embedding instead of the ring
            def embed_only(e):
                return embed_fn(e, x[mb_i])
            _, emb_vjp = jax.vjp(embed_only, emb)
            (demb,) = emb_vjp(dx)
            take_emb = mb_ok & (stage == 0)
            gacc["embed"] = jax.tree.map(
                lambda a, g: a + jnp.where(take_emb, g, 0.0).astype(a.dtype),
                gacc["embed"], demb)

            fwd_recv = lax.ppermute(out, pipe_axis, fwd_perm)
            bwd_recv = lax.ppermute(dx, pipe_axis, bwd_perm)
            return (fwd_recv, bwd_recv, stash, seeds, gacc, loss_acc), None

        init = (
            jnp.zeros((mb, t, d_model), cdt),
            jnp.zeros((mb, t, d_model), cdt),
            jnp.zeros((W, mb, t, d_model), cdt),
            jnp.zeros((2, mb, t, d_model), cdt),
            zeros_g,
            jnp.zeros((), jnp.float32),
        )
        (_, _, _, _, gacc, loss_acc), _ = lax.scan(
            tick, init, jnp.arange(M + 2 * S - 1))

        # flush: per-microbatch means -> full-batch mean
        loss = lax.psum(loss_acc, pipe_axis) / M
        grads = {
            "embed": jax.tree.map(
                lambda g: lax.psum(g, pipe_axis) / M, gacc["embed"]),
            # stage grads live where the stage lives; restack the leading
            # stage dim so the tree matches pp_params["stages"]
            "stages": jax.tree.map(
                lambda g: g[None] / M, gacc["stages"]),
            "tail": jax.tree.map(
                lambda g: lax.psum(g, pipe_axis) / M, gacc["tail"]),
        }
        if data_axis is not None:
            loss = lax.pmean(loss, data_axis)
            grads = jax.tree.map(lambda g: lax.pmean(g, data_axis), grads)
        return loss, grads

    batch_spec = P(None, data_axis) if data_axis else P()
    smap_kwargs = {}
    if manual_axes is not None:
        # axes not listed (a tensor-parallel "model" axis on a 3-D mesh)
        # stay automatic: GSPMD partitions the per-stage math and the
        # per-stage vjp from the argument shardings (pp_tp_shardings),
        # exactly as on the GPipe path
        smap_kwargs["axis_names"] = frozenset(manual_axes)
    smapped = shard_map(
        per_device, mesh=mesh,
        in_specs=({"embed": P(), "stages": P(pipe_axis), "tail": P()},
                  batch_spec, batch_spec, P()),
        out_specs=(P(), {"embed": P(), "stages": P(pipe_axis), "tail": P()}),
        check_vma=False,
        **smap_kwargs,
    )

    def step(pp_params, opt_state, x, y, rng):
        n, t = x.shape
        assert n % n_microbatches == 0, (n, n_microbatches)
        if data_axis is not None:
            mbs = n // n_microbatches
            assert mbs % mesh.shape[data_axis] == 0, (
                f"microbatch size {mbs} must divide over the "
                f"'{data_axis}' axis ({mesh.shape[data_axis]} devices)")
        xm = x.reshape(n_microbatches, n // n_microbatches, t)
        ym = y.reshape(n_microbatches, n // n_microbatches, t)
        loss, grads = smapped(pp_params, xm, ym, rng)
        new_params, new_opt = optim_method.update(grads, opt_state,
                                                  pp_params)
        return new_params, new_opt, loss

    return jax.jit(step, donate_argnums=(0, 1))


def init_pp_opt_state(optim_method, pp_params, mesh, pipe_axis="pipe"):
    """Optimizer state device_put with the same shardings as its params."""
    from bigdl_tpu.parallel.zero import shard_opt_state

    ps = pp_shardings(pp_params, mesh, pipe_axis)
    return shard_opt_state(optim_method, pp_params, ps, mesh)
