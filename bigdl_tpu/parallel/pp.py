"""Pipeline parallelism (GPipe schedule) over a ``pipe`` mesh axis.

No reference analogue (SURVEY.md section 2.4: pipeline parallelism absent) --
built the canonical TPU way: transformer blocks are split into ``n_stages``
contiguous stages whose parameters are *stacked* on a leading stage dimension
and sharded over the ``pipe`` mesh axis.  Inside ``shard_map`` every device
runs its own stage; activations move stage->stage with a single
``lax.ppermute`` hop per schedule tick (nearest-neighbour on the ICI ring,
the cheapest collective there is).  The schedule is the classic GPipe loop:
``n_micro + n_stages - 1`` ticks, each device computing every tick (bubble
ticks compute garbage that is masked out), microbatch *t* entering stage 0 at
tick *t* and leaving the last stage at tick ``t + n_stages - 1``.

Autodiff runs straight through the schedule: the transpose of ``ppermute`` is
the reverse-ring ``ppermute``, so ``jax.grad`` of the shard_map'd loss *is*
the 1F1B-ish backward pipeline -- no hand-written backward schedule.

Embedding and the LM head are computed replicated (they are cheap relative
to the blocks); only the block stack is pipelined.  Composes with data
parallelism via a 2-D ``(data, pipe)`` mesh: the batch is sharded over
``data`` and shard_map's transpose machinery inserts the gradient psums.
"""

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from bigdl_tpu.nn.module import child_rng


def stack_stage_params(model, n_stages: int):
    """Split a built TransformerLM's blocks into ``n_stages`` stacked stages.

    -> dict with
       ``embed``:  {wte, wpe}                       (replicated)
       ``stages``: {layer{j}: block-params-stacked} (leading dim = stage)
       ``tail``:   {ln_f, head}                     (replicated)
    """
    params = model._params
    n_layers = len(model.blocks)
    assert n_layers % n_stages == 0, (n_layers, n_stages)
    lps = n_layers // n_stages
    stages = {}
    for j in range(lps):
        per_stage = [params[f"block{s * lps + j}"] for s in range(n_stages)]
        stages[f"layer{j}"] = jax.tree.map(
            lambda *xs: jnp.stack(xs), *per_stage)
    return {
        "embed": {"wte": params["wte"], "wpe": params["wpe"]},
        "stages": stages,
        "tail": {"ln_f": params["ln_f"], "head": params["head"]},
    }


def unstack_stage_params(model, pp_params):
    """Inverse of stack_stage_params -> plain TransformerLM params dict."""
    out = {"wte": pp_params["embed"]["wte"], "wpe": pp_params["embed"]["wpe"],
           "ln_f": pp_params["tail"]["ln_f"],
           "head": pp_params["tail"]["head"]}
    stages = pp_params["stages"]
    lps = len(stages)
    n_stages = jax.tree.leaves(stages["layer0"])[0].shape[0]
    for s in range(n_stages):
        for j in range(lps):
            out[f"block{s * lps + j}"] = jax.tree.map(
                lambda a: a[s], stages[f"layer{j}"])
    return out


def pp_shardings(pp_params, mesh, pipe_axis="pipe"):
    """NamedShardings: stage-stacked leaves sharded on dim 0, rest replicated."""
    rep = NamedSharding(mesh, P())
    staged = NamedSharding(mesh, P(pipe_axis))
    return {
        "embed": jax.tree.map(lambda _: rep, pp_params["embed"]),
        "stages": jax.tree.map(lambda _: staged, pp_params["stages"]),
        "tail": jax.tree.map(lambda _: rep, pp_params["tail"]),
    }


def pp_tp_shardings(pp_params, mesh, pipe_axis="pipe", model_axis="model",
                    rules=None):
    """3-D composition shardings: stage-stacked leaves sharded over
    ``pipe`` on dim 0 AND Megatron-style over ``model`` on their weight
    dims (TRANSFORMER_TP_RULES shifted by the stage dimension); embed/tail
    replicated.  Use with make_pp_train_step(..., manual_axes=
    ("data", "pipe")) so the model axis stays automatic (GSPMD)."""
    import re

    from jax.tree_util import keystr, tree_flatten_with_path, tree_unflatten

    from bigdl_tpu.parallel.tp import TRANSFORMER_TP_RULES

    rules = rules if rules is not None else TRANSFORMER_TP_RULES
    rep = NamedSharding(mesh, P())

    def stage_shardings(tree):
        leaves, treedef = tree_flatten_with_path(tree)
        out = []
        for path, leaf in leaves:
            name = keystr(path)
            spec = [pipe_axis] + [None] * (leaf.ndim - 1)
            for pattern, dims in rules:
                if re.search(pattern, name):
                    if len(dims) == leaf.ndim - 1:
                        spec = [pipe_axis] + [
                            d if d is None else model_axis for d in dims]
                    break
            out.append(NamedSharding(mesh, P(*spec)))
        return tree_unflatten(treedef, out)

    return {
        "embed": jax.tree.map(lambda _: rep, pp_params["embed"]),
        "stages": stage_shardings(pp_params["stages"]),
        "tail": jax.tree.map(lambda _: rep, pp_params["tail"]),
    }


def make_pp_loss_fn(model, criterion, mesh, n_microbatches: int,
                    pipe_axis: str = "pipe",
                    data_axis: Optional[str] = None,
                    manual_axes: Optional[tuple] = None,
                    compute_dtype=None):
    """-> loss(pp_params, x_tokens, y_tokens) with the GPipe schedule inside.

    ``x``/``y``: int32 (batch, T); batch must divide n_microbatches (times
    the data-axis size when present).

    ``manual_axes``: mesh axes handled manually by this shard_map; axes NOT
    listed (e.g. a ``model`` tensor-parallel axis on a 3-D mesh) stay
    automatic -- GSPMD partitions the per-stage math over them from the
    argument shardings (pp_tp_shardings).  Default: all mesh axes manual
    (the 2-D data x pipe case).
    """
    n_stages = mesh.shape[pipe_axis]
    lps = len(model.blocks) // n_stages

    def stage_fn(stage_params, x, rng):
        for j in range(lps):
            x, _ = model.blocks[0].apply(
                stage_params[f"layer{j}"], (), x, training=True,
                rng=child_rng(rng, j))
        return x

    def per_device(pp_params, x, y, rng):
        # x, y: (n_micro, mb_local, T) on this device
        from bigdl_tpu.optim.train_step import _cast_tree
        pp_params = _cast_tree(pp_params, compute_dtype)
        cdt = compute_dtype or jnp.float32
        stage = lax.axis_index(pipe_axis)
        sp = jax.tree.map(lambda a: a[0], pp_params["stages"])
        emb = pp_params["embed"]
        n_micro, mb, t = x.shape

        def embed(tok):
            h = jnp.take(emb["wte"], tok, axis=0)
            return h + emb["wpe"][:t][None]

        d = emb["wte"].shape[1]
        fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, tk):
            recv, outs = carry
            mb_idx = jnp.clip(tk, 0, n_micro - 1)
            inp = jnp.where(stage == 0, embed(x[mb_idx]), recv)
            out = stage_fn(sp, inp, child_rng(child_rng(rng, 7), tk))
            out_idx = tk - (n_stages - 1)
            valid = (stage == n_stages - 1) & (out_idx >= 0)
            widx = jnp.clip(out_idx, 0, n_micro - 1)
            outs = outs.at[widx].set(jnp.where(valid, out, outs[widx]))
            send = lax.ppermute(out, pipe_axis, fwd_perm)
            return (send, outs), None

        init = (jnp.zeros((mb, t, d), cdt),
                jnp.zeros((n_micro, mb, t, d), cdt))
        (_, outs), _ = lax.scan(tick, init,
                                jnp.arange(n_micro + n_stages - 1))
        # replicated tail on the collected last-stage activations
        h = outs.reshape(n_micro * mb, t, d)
        h, _ = model.ln_f.apply(pp_params["tail"]["ln_f"], (), h)
        logits = h @ pp_params["tail"]["head"].astype(h.dtype).T
        loss_local = criterion.apply(logits.astype(jnp.float32),
                                     y.reshape(n_micro * mb, t))
        loss = lax.psum(
            jnp.where(stage == n_stages - 1, loss_local, 0.0), pipe_axis)
        if data_axis is not None:
            loss = lax.pmean(loss, data_axis)
        return loss

    batch_spec = P(None, data_axis) if data_axis else P()
    smap_kwargs = {}
    if manual_axes is not None:
        smap_kwargs["axis_names"] = frozenset(manual_axes)
    smapped = jax.shard_map(
        per_device, mesh=mesh,
        in_specs=({"embed": P(), "stages": P(pipe_axis), "tail": P()},
                  batch_spec, batch_spec, P()),
        out_specs=P(),
        check_vma=False,
        **smap_kwargs,
    )

    def loss_fn(pp_params, x, y, rng=None):
        n, t = x.shape
        assert n % n_microbatches == 0, (n, n_microbatches)
        if data_axis is not None:
            mb = n // n_microbatches
            assert mb % mesh.shape[data_axis] == 0, (
                f"microbatch size {mb} must divide over the "
                f"'{data_axis}' axis ({mesh.shape[data_axis]} devices)")
        xm = x.reshape(n_microbatches, n // n_microbatches, t)
        ym = y.reshape(n_microbatches, n // n_microbatches, t)
        if rng is None:
            rng = jax.random.key(0)
        return smapped(pp_params, xm, ym, rng)

    return loss_fn


def make_pp_train_step(model, criterion, optim_method, mesh,
                       n_microbatches: int, pipe_axis: str = "pipe",
                       data_axis: Optional[str] = None,
                       manual_axes: Optional[tuple] = None,
                       compute_dtype=None):
    """-> jitted step(pp_params, opt_state, x, y, rng) -> (params', opt', loss).

    Stage-stacked params (and their optimizer moments) live sharded over the
    ``pipe`` axis; the update runs where the shard lives (optimizer-state
    parallelism, the pipeline analogue of the reference's chunk ownership in
    parameters/AllReduceParameter.scala:84).  ``manual_axes``: see
    make_pp_loss_fn -- pass ("data", "pipe") on a 3-D data x pipe x model
    mesh to compose with GSPMD tensor parallelism.
    """
    from bigdl_tpu.nn.module import has_frozen
    if has_frozen(model):
        raise NotImplementedError(
            "freeze() is honored by make_train_step and the "
            "DistriOptimizer flat-chunk step; this model-parallel engine "
            "does not mask frozen parameters yet -- unfreeze() before "
            "building, or train with LocalOptimizer/DistriOptimizer")
    loss_fn = make_pp_loss_fn(model, criterion, mesh, n_microbatches,
                              pipe_axis, data_axis, manual_axes,
                              compute_dtype)

    def step(pp_params, opt_state, x, y, rng):
        loss, grads = jax.value_and_grad(loss_fn)(pp_params, x, y, rng)
        new_params, new_opt = optim_method.update(grads, opt_state, pp_params)
        return new_params, new_opt, loss

    return jax.jit(step, donate_argnums=(0, 1))


def init_pp_opt_state(optim_method, pp_params, mesh, pipe_axis="pipe"):
    """Optimizer state device_put with the same shardings as its params."""
    from bigdl_tpu.parallel.zero import shard_opt_state

    ps = pp_shardings(pp_params, mesh, pipe_axis)
    return shard_opt_state(optim_method, pp_params, ps, mesh)
