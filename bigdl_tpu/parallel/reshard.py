"""Portable resharding: one saved layout in, any target layout out.

ROADMAP item 3.  A checkpoint is only as elastic as its layout is
portable: the reference gets this for free from Spark lineage (BigDL,
arxiv 1804.05839 section 3 -- state lives in RDDs, any executor count
re-materializes it), and the dp slice of our TPU rebuild got it in PR 8
(the flat plane re-chunks N->M).  The tp/pp/ep strategy snapshots were
still welded to the mesh they were written on, and the serving engine
assumed the training and serving layouts match.  This module is the
redistribution layer that unwelds them, in the family of
memory-efficient array redistribution through portable collectives
(arxiv 2112.01075): the heavy lifting happens on HOST trees restored
under the snapshot's OWN layout (replicated logical arrays -- no
cross-layout resharding strictness for orbax/old-jax to trip), as pure
structural transformations; device placement afterwards is the caller's
ordinary ``device_put`` onto its live shardings.

Two pieces:

- ``LayoutSpec``: a JSON-able description of how a saved tree is laid
  out -- strategy kind, mesh axes/degrees, per-plane partition spec --
  stamped into every sharded-snapshot manifest (``layout`` block,
  extending PR 8's dp-only block, whose legacy spelling still parses).
- ``redistribute(tree, src, dst)``: maps a host tree between layouts:
  dp N->M chunk-layout resize (``zero.refit_flat_plane`` /
  ``zero.repartition_ef_residual`` walks, subsuming the PR 8 closures),
  pp stage re-cutting (stage-stacked <-> per-block trees, the
  ``stack_block_params``/``unstack_block_params`` interconversion
  generalized to any mirrored subtree, e.g. Adam moments), scan <->
  unrolled block-layout conversion, and tp/ep/sp <-> replicated (the
  logical tree is identical; the conversion is a layout *statement*, so
  serving can accept any of them).  Every redistribution emits a
  durable ``kind: "reshard"`` telemetry event (src/dst layout, planes
  moved, host bytes, wall seconds) -- the audit trail behind an elastic
  restart or a cross-layout serving refresh (docs/robustness.md,
  "Portable resharding").

No jax import at module top: a supervisor or report process can parse
``LayoutSpec`` manifests without an accelerator backend; the tree
transformations import jax lazily.
"""

import dataclasses
import logging
import re
import time
from typing import Any, Dict, Optional

log = logging.getLogger("bigdl_tpu.parallel")

#: layout kinds a LayoutSpec may carry.  "replicated" is the serving /
#: single-device layout: the model's own tree, whole on every device.
LAYOUT_KINDS = ("dp", "tp", "pp", "sp", "ep", "replicated")

#: transformer block-keying layouts (nn.attention): per-block
#: ``block{i}`` entries vs one stacked ``blocks`` entry (scan_layers)
BLOCK_LAYOUTS = ("unrolled", "scan")

_BLOCK_KEY = re.compile(r"^block(\d+)$")

#: manifest keys that are LayoutSpec structure, not per-plane detail
_SPEC_KEYS = ("kind", "mesh_axes", "block_layout")


def _jsonable(v):
    """Tuples -> lists (deep), so a spec built in python compares equal
    to the same spec round-tripped through a JSON manifest."""
    if isinstance(v, tuple):
        return [_jsonable(x) for x in v]
    if isinstance(v, list):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {k: _jsonable(x) for k, x in v.items()}
    return v


@dataclasses.dataclass
class LayoutSpec:
    """How a saved param/opt-state tree is laid out.

    ``kind``       -- one of ``LAYOUT_KINDS``.
    ``mesh_axes``  -- axis name -> degree of the mesh the layout was
                      built for (``{"data": 2, "model": 4}``).
    ``plane``      -- kind-specific per-plane partition spec:
                      dp: ``padded_size/true_size/num_chunks/block_size/
                      ef_shape`` (PR 8's block, verbatim);
                      tp/ep: the path-regex ``rules`` and the sharded
                      ``axis``; pp: ``n_stages/pipe_axis/
                      tensor_parallel``.
    ``block_layout`` -- transformer block keying of the tree
                      (``"unrolled"`` / ``"scan"``), or None when the
                      model family has no block keying.

    Serializes to the snapshot manifest's ``layout`` block via
    ``to_manifest`` (plane keys flattened to the top level, so PR 8's
    dp-only readers keep working) and parses back via
    ``from_manifest`` (a legacy kind-less dp block still loads).
    """

    kind: str
    mesh_axes: Dict[str, int] = dataclasses.field(default_factory=dict)
    plane: Dict[str, Any] = dataclasses.field(default_factory=dict)
    block_layout: Optional[str] = None

    def __post_init__(self):
        if self.kind not in LAYOUT_KINDS:
            raise ValueError(f"unknown layout kind {self.kind!r}; "
                             f"expected one of {LAYOUT_KINDS}")
        if self.block_layout is not None \
                and self.block_layout not in BLOCK_LAYOUTS:
            raise ValueError(
                f"unknown block_layout {self.block_layout!r}; expected "
                f"one of {BLOCK_LAYOUTS} or None")
        self.mesh_axes = {str(k): int(v) for k, v in
                          (self.mesh_axes or {}).items()}
        self.plane = _jsonable(dict(self.plane or {}))

    # ----- constructors ---------------------------------------------------- #
    @classmethod
    def dp(cls, num_chunks, padded_size, true_size, block_size=1,
           ef_shape=None, axis="data"):
        """The ZeRO-1 flat-plane layout (PR 8's manifest block)."""
        return cls("dp", {axis: int(num_chunks)},
                   {"padded_size": int(padded_size),
                    "true_size": int(true_size),
                    "num_chunks": int(num_chunks),
                    "block_size": int(block_size),
                    "ef_shape": (None if ef_shape is None
                                 else [int(s) for s in ef_shape])})

    @classmethod
    def tp(cls, mesh_axes, axis="model", rules=None, block_layout=None):
        plane = {"axis": axis}
        if rules is not None:
            plane["rules"] = [[p, list(d)] for p, d in rules]
        return cls("tp", mesh_axes, plane, block_layout)

    @classmethod
    def ep(cls, mesh_axes, axis="expert", rules=None, num_experts=None):
        plane = {"axis": axis}
        if rules is not None:
            plane["rules"] = [[p, list(d)] for p, d in rules]
        if num_experts is not None:
            # the expert-count the tree's stacked leading dims hold --
            # what an ep -> ep expert-count re-cut converts between
            plane["num_experts"] = int(num_experts)
        return cls("ep", mesh_axes, plane)

    @classmethod
    def pp(cls, mesh_axes, n_stages, pipe_axis="pipe",
           tensor_parallel=False):
        return cls("pp", mesh_axes,
                   {"n_stages": int(n_stages), "pipe_axis": pipe_axis,
                    "tensor_parallel": bool(tensor_parallel)})

    @classmethod
    def sp(cls, mesh_axes, seq_axis="seq", block_layout=None):
        return cls("sp", mesh_axes, {"axis": seq_axis}, block_layout)

    @classmethod
    def replicated(cls, block_layout=None):
        return cls("replicated", {}, {}, block_layout)

    @classmethod
    def for_model(cls, model):
        """The ``replicated`` layout of a built model's OWN tree --
        what a serving engine or a single-device resume wants --
        detecting the transformer block keying from the params."""
        return cls.replicated(
            block_layout=detect_block_layout(model.parameters()[0]))

    # ----- manifest round trip --------------------------------------------- #
    def to_manifest(self) -> dict:
        out = {"kind": self.kind}
        if self.mesh_axes:
            out["mesh_axes"] = dict(self.mesh_axes)
        if self.block_layout is not None:
            out["block_layout"] = self.block_layout
        out.update(self.plane)
        return out

    @classmethod
    def from_manifest(cls, block) -> Optional["LayoutSpec"]:
        """Parse a manifest ``layout`` block; None passes through.  A
        legacy PR 8 block (no ``kind`` -- only the dp saver stamped
        one) parses as dp."""
        if not block:
            return None
        d = dict(block)
        kind = d.pop("kind", "dp")
        mesh_axes = d.pop("mesh_axes", None) or {}
        block_layout = d.pop("block_layout", None)
        if kind == "dp" and not mesh_axes and "num_chunks" in d:
            mesh_axes = {"data": int(d["num_chunks"])}
        return cls(kind, mesh_axes, d, block_layout)

    @classmethod
    def coerce(cls, spec) -> "LayoutSpec":
        if isinstance(spec, cls):
            return spec
        if isinstance(spec, dict):
            out = cls.from_manifest(spec)
            if out is not None:
                return out
        raise ValueError(f"cannot interpret {spec!r} as a LayoutSpec")

    # ----- accessors -------------------------------------------------------- #
    def degree(self, axis, default=1) -> int:
        return int(self.mesh_axes.get(axis, default))

    @property
    def n_stages(self):
        return int(self.plane["n_stages"]) if "n_stages" in self.plane \
            else None

    def describe(self) -> str:
        """Short human label: ``tp[data=2,model=4]``, ``dp[data=8]``."""
        axes = ",".join(f"{k}={v}" for k, v in sorted(self.mesh_axes.items()))
        extra = ""
        if self.kind == "pp" and self.n_stages is not None:
            extra = f"/stages={self.n_stages}"
        if self.block_layout == "scan":
            extra += "/scan"
        return f"{self.kind}[{axes}]{extra}" if axes \
            else f"{self.kind}{extra}"

    def __eq__(self, other):
        if not isinstance(other, LayoutSpec):
            return NotImplemented
        return (self.kind == other.kind
                and self.mesh_axes == other.mesh_axes
                and _jsonable(self.plane) == _jsonable(other.plane)
                and self.block_layout == other.block_layout)


def detect_block_layout(params) -> Optional[str]:
    """``"scan"`` / ``"unrolled"`` / None from a params tree's keying
    (the TransformerLM layouts ``stack_block_params`` interconverts)."""
    if not isinstance(params, dict):
        return None
    if "blocks" in params:
        return "scan"
    if any(_BLOCK_KEY.match(k) for k in params):
        return "unrolled"
    return None


def read_snapshot_layout(path) -> Optional[LayoutSpec]:
    """The LayoutSpec stamped into a snapshot's sidecar manifest, or
    None (legacy manifest-less snapshot, or a pre-PR-12 strategy
    snapshot that recorded no layout)."""
    from bigdl_tpu.utils import file_io

    manifest = file_io.read_manifest(path) or {}
    return LayoutSpec.from_manifest(manifest.get("layout"))


# --------------------------------------------------------------------------- #
# Structural conversions (pure; operate on host / abstract trees).
# --------------------------------------------------------------------------- #


def _is_pp_tree(t) -> bool:
    return isinstance(t, dict) and set(t) == {"embed", "stages", "tail"}


def _has_block_keys(t) -> bool:
    return isinstance(t, dict) and ("blocks" in t
                                    or any(_BLOCK_KEY.match(k) for k in t))


def pp_tree_to_blocks(pp_tree):
    """Stage-stacked pp params (``{embed, stages, tail}``,
    ``parallel/pp.stack_stage_params`` layout) -> the plain per-block
    TransformerLM tree, as a PURE tree transformation (no model object
    needed -- it also applies to optimizer-moment subtrees that mirror
    the params).  Inverse of ``blocks_to_pp_tree``."""
    import jax

    stages = pp_tree["stages"]
    lps = len(stages)
    n_stages = int(jax.tree.leaves(stages["layer0"])[0].shape[0])
    out = {"wte": pp_tree["embed"]["wte"], "wpe": pp_tree["embed"]["wpe"],
           "ln_f": pp_tree["tail"]["ln_f"], "head": pp_tree["tail"]["head"]}
    for s in range(n_stages):
        for j in range(lps):
            out[f"block{s * lps + j}"] = jax.tree.map(
                lambda a, _s=s: a[_s], stages[f"layer{j}"])
    return out


def blocks_to_pp_tree(tree, n_stages):
    """Plain per-block TransformerLM tree -> the ``n_stages``
    stage-stacked pp layout (``parallel/pp.stack_stage_params``
    semantics, model-free).  The block count must divide evenly into
    the stages -- anything else is a re-cut the pipeline engine cannot
    address."""
    import jax
    import jax.numpy as jnp

    idx = sorted(int(m.group(1)) for k in tree
                 if (m := _BLOCK_KEY.match(k)))
    if not idx or idx != list(range(len(idx))):
        raise ValueError(
            f"cannot stage-stack: expected contiguous block0..blockN "
            f"entries, got {sorted(k for k in tree)[:8]}")
    n_layers = len(idx)
    n_stages = int(n_stages)
    if n_layers % n_stages:
        raise ValueError(
            f"cannot re-cut {n_layers} blocks into {n_stages} pipeline "
            f"stages: block count must divide evenly")
    lps = n_layers // n_stages
    stages = {}
    for j in range(lps):
        per_stage = [tree[f"block{s * lps + j}"] for s in range(n_stages)]
        stages[f"layer{j}"] = jax.tree.map(
            lambda *xs: jnp.stack(xs), *per_stage)
    return {
        "embed": {"wte": tree["wte"], "wpe": tree["wpe"]},
        "stages": stages,
        "tail": {"ln_f": tree["ln_f"], "head": tree["head"]},
    }


def detect_num_experts(params) -> Optional[int]:
    """The expert count of the first MoE-shaped subtree in ``params``
    (``nn/moe.py`` keying: ``gate (D, E)`` beside expert-stacked
    ``w1 (E, D, F)``), or None for expert-free models -- what the ep
    layout stamp records so an expert-count re-cut knows both sides."""
    found = []

    def look(d):
        if _is_moe_node(d) and not found:
            found.append(int(d["gate"].shape[-1]))
        return None

    _walk_dicts(params, look)
    return found[0] if found else None


def _is_moe_node(d) -> bool:
    """An ``nn/moe.py``-shaped params dict (or an optimizer-moment
    subtree mirroring one): a 2-D router ``gate`` whose logits dim
    matches the leading expert-stacked dim of a 3-D ``w1``."""
    if not isinstance(d, dict) or not {"gate", "w1", "w2"} <= set(d):
        return False
    gate, w1 = d.get("gate"), d.get("w1")
    return (getattr(gate, "ndim", 0) == 2 and getattr(w1, "ndim", 0) == 3
            and gate.shape[-1] == w1.shape[0])


def _reexpert(tree, src_e, dst_e):
    """ep -> ep expert-count re-cut, applied to every MoE-shaped
    subtree (params AND mirrored Adam moments): the expert-stacked
    leading dims re-cut like pp stages, and the router's gate logits
    plane re-sizes to match the new expert count.

    - GROW (``dst_e = k * src_e``): each expert splits into ``k``
      consecutive bit-identical replicas (expert ``i`` -> rows
      ``k*i .. k*i+k-1``) and the gate grows a logit column per
      replica (copied, so the router's preference order is preserved;
      with top-k routing the replicas then share their ancestor's
      traffic -- a warm-start re-cut, the MoE upcycling stance).
    - SHRINK (``src_e = k * dst_e``): the exact inverse -- each
      consecutive group of ``k`` experts must be BIT-IDENTICAL (i.e.
      an earlier grow that training has not yet diverged) and merges
      back to its first member.  Genuinely distinct experts cannot be
      merged and raise instead of silently averaging information away.

    Grow -> shrink is therefore bit-identical (the A->B->A property
    pin, like the dp/pp/tp conversions)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    src_e, dst_e = int(src_e), int(dst_e)
    if src_e == dst_e:
        return tree
    if dst_e % src_e and src_e % dst_e:
        raise ValueError(
            f"cannot re-cut {src_e} experts into {dst_e}: expert counts "
            f"must divide evenly (grow k-for-1 or merge k-to-1)")

    def grow(d, k):
        out = dict(d)
        for key, a in d.items():
            if not hasattr(a, "shape"):
                continue
            if key == "gate":
                out[key] = jnp.repeat(jnp.asarray(a), k, axis=-1)
            elif a.ndim >= 1 and a.shape[0] == src_e:
                out[key] = jnp.repeat(jnp.asarray(a), k, axis=0)
        return out

    def _concrete(a):
        """Host numpy view of a leaf, or None under an abstract trace
        (``convert_shapes``) -- where the replica-identity check is
        meaningless and only the shapes matter."""
        try:
            return np.asarray(a)
        except Exception:
            return None

    def shrink(d, k):
        out = dict(d)
        for key, a in d.items():
            if not hasattr(a, "shape"):
                continue
            if key == "gate":
                g = jnp.reshape(jnp.asarray(a),
                                tuple(a.shape[:-1]) + (dst_e, k))
                gc = _concrete(g)
                if gc is not None and not (gc == gc[..., :1]).all():
                    raise ValueError(
                        f"cannot merge {src_e} experts into {dst_e}: "
                        f"gate logit columns of a replica group differ "
                        f"-- these are genuinely distinct experts, not "
                        f"an undiverged grow")
                out[key] = g[..., 0]
            elif a.ndim >= 1 and a.shape[0] == src_e:
                g = jnp.reshape(jnp.asarray(a), (dst_e, k) + a.shape[1:])
                gc = _concrete(g)
                if gc is not None and not (gc == gc[:, :1]).all():
                    raise ValueError(
                        f"cannot merge {src_e} experts into {dst_e}: "
                        f"expert plane {key!r} differs within a replica "
                        f"group -- these are genuinely distinct "
                        f"experts, not an undiverged grow")
                out[key] = g[:, 0]
        return out

    def convert(d):
        if not _is_moe_node(d) or d["gate"].shape[-1] != src_e:
            return None
        return grow(d, dst_e // src_e) if dst_e > src_e \
            else shrink(d, src_e // dst_e)

    return _walk_dicts(tree, convert)


def _walk_dicts(tree, fn):
    """Apply ``fn`` to every dict node top-down; when ``fn`` returns a
    replacement (non-None), recursion stops for that subtree."""
    if isinstance(tree, dict):
        replaced = fn(tree)
        if replaced is not None:
            return replaced
        return {k: _walk_dicts(v, fn) for k, v in tree.items()}
    return tree


def _reblock(tree, src_bl, dst_bl):
    """scan <-> unrolled transformer block keying, applied to every
    subtree that carries block keys (params AND mirrored moments)."""
    if src_bl == dst_bl or src_bl is None or dst_bl is None:
        return tree
    from bigdl_tpu.nn.attention import (stack_block_params,
                                        unstack_block_params)

    def convert(d):
        if dst_bl == "unrolled" and "blocks" in d:
            return unstack_block_params(d)
        if dst_bl == "scan" and any(_BLOCK_KEY.match(k) for k in d):
            return stack_block_params(d)
        return None

    return _walk_dicts(tree, convert)


def _restage(tree, src, dst):
    """pp stage re-cutting / pp <-> model-tree restructuring, applied
    recursively so optimizer-state dicts whose values mirror the params
    tree convert too."""
    src_pp = src.kind == "pp"
    dst_pp = dst.kind == "pp"
    if not src_pp and not dst_pp:
        return tree

    def convert(d):
        if src_pp and _is_pp_tree(d):
            blocks = pp_tree_to_blocks(d)
            return blocks_to_pp_tree(blocks, dst.n_stages) if dst_pp \
                else blocks
        if not src_pp and dst_pp and _has_block_keys(d):
            return blocks_to_pp_tree(d, dst.n_stages)
        return None

    return _walk_dicts(tree, convert)


def _convert_dp(tree, src, dst):
    """dp -> dp chunk-layout resize: flat planes pad/truncate their
    trailing padding (``zero.refit_flat_plane``); the EF-SGD residual
    plane re-partitions by global flat offset
    (``zero.repartition_ef_residual``); everything else (scalars,
    mstate leaves) passes through."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.parallel.zero import (refit_flat_plane,
                                         repartition_ef_residual)

    if int(src.plane["true_size"]) != int(dst.plane["true_size"]):
        raise ValueError(
            f"dp layouts hold different parameter counts "
            f"({src.plane['true_size']} vs {dst.plane['true_size']}): "
            "this is a different model, not a chunk-layout change")
    src_padded = int(src.plane["padded_size"])
    dst_padded = int(dst.plane["padded_size"])
    true = int(dst.plane["true_size"])
    src_ef = src.plane.get("ef_shape")
    dst_ef = dst.plane.get("ef_shape")

    def fix(a):
        a = jnp.asarray(a)
        if src_ef and dst_ef and a.ndim == 2 \
                and tuple(a.shape) == tuple(src_ef):
            if a.shape[0] == int(dst.plane["num_chunks"]):
                # same device count (a block-rounding-only change):
                # each row is still that device's own accumulated
                # error -- trailing pad/truncate keeps rows verbatim
                # (exact), matching the PR 8 restore semantics
                return refit_flat_plane(a, dst_padded, true)
            return jnp.asarray(repartition_ef_residual(
                a, true, int(dst.plane["num_chunks"]), dst_padded))
        if a.ndim >= 1 and a.shape[-1] == src_padded:
            return refit_flat_plane(a, dst_padded, true)
        return a

    return jax.tree.map(fix, tree)


def _convert(tree, src, dst):
    if src.kind == "dp" or dst.kind == "dp":
        if src.kind == dst.kind == "dp":
            return _convert_dp(tree, src, dst)
        raise ValueError(
            f"cannot redistribute {src.kind} -> {dst.kind} directly: "
            "the dp layout is a FLAT plane; convert through the model "
            "tree with flat_to_tree/tree_to_flat (they need the "
            "model's tree as the unravel template)")
    if src.kind == "ep" and dst.kind == "ep":
        se = src.plane.get("num_experts")
        de = dst.plane.get("num_experts")
        if se is not None and de is not None and int(se) != int(de):
            tree = _reexpert(tree, se, de)
    out = _restage(tree, src, dst)
    # pp trees are unrolled by construction on both sides of _restage
    src_bl = "unrolled" if src.kind == "pp" else src.block_layout
    dst_bl = "unrolled" if dst.kind == "pp" else dst.block_layout
    return _reblock(out, src_bl, dst_bl)


def convert_shapes(tree, src, dst):
    """``redistribute`` on SHAPES only (``jax.eval_shape``): what a
    caller uses to derive the snapshot-native abstract tree for an
    orbax restore from its live tree (dst -> src direction).  dp
    layouts are excluded (the residual re-partition is a host numpy
    op); their shapes are directly computable from the plane spec."""
    import jax

    return jax.eval_shape(lambda t: _convert(t, src, dst), tree)


def flat_to_tree(flat, layout, tree_template):
    """dp flat plane -> the model's own params tree.  ``tree_template``
    supplies the unravel bijection (the model's built params);
    ``layout`` guards that the plane actually holds this model."""
    import jax.numpy as jnp

    from bigdl_tpu.parallel.zero import FlatParamSpace

    layout = LayoutSpec.coerce(layout)
    space = FlatParamSpace(tree_template, 1)
    true = int(layout.plane.get("true_size", space.true_size))
    if true != space.true_size:
        raise ValueError(
            f"dp flat plane holds {true} parameters but the target "
            f"model tree holds {space.true_size}: different model")
    flat = jnp.asarray(flat)
    if flat.shape[-1] < space.true_size:
        raise ValueError(
            f"flat plane of {flat.shape[-1]} elements cannot fill a "
            f"{space.true_size}-parameter tree")
    return space.unflatten(
        jnp.pad(flat, (0, max(0, space.padded_size - flat.size))))


def tree_to_flat(tree, layout):
    """Model params tree -> a dp flat plane under ``layout``'s chunk
    rounding (the inverse of ``flat_to_tree``)."""
    from bigdl_tpu.parallel.zero import FlatParamSpace

    layout = LayoutSpec.coerce(layout)
    space = FlatParamSpace(tree, int(layout.plane["num_chunks"]),
                           int(layout.plane.get("block_size", 1)))
    if space.padded_size != int(layout.plane["padded_size"]):
        raise ValueError(
            f"tree flattens to padded size {space.padded_size}, layout "
            f"says {layout.plane['padded_size']}: different model or "
            "block rounding")
    return space.flatten(tree)


# --------------------------------------------------------------------------- #
# The engine: redistribute + audit event.
# --------------------------------------------------------------------------- #


def _tree_stats(tree):
    import jax

    leaves = [l for l in jax.tree.leaves(tree)
              if hasattr(l, "nbytes")]
    return len(leaves), int(sum(int(l.nbytes) for l in leaves))


def record_reshard_event(telemetry, src, dst, what, planes, host_bytes,
                         wall_s):
    """Emit the durable ``kind: "reshard"`` audit event (None telemetry
    is a no-op; a failing record must never fail the restore that
    triggered it)."""
    if telemetry is None:
        return None
    try:
        return telemetry.record(
            "reshard", src=src.describe(), dst=dst.describe(),
            src_layout=src.to_manifest(), dst_layout=dst.to_manifest(),
            what=what, planes=planes, host_bytes=host_bytes,
            wall_s=round(float(wall_s), 6))
    except Exception:
        log.exception("reshard telemetry record failed")
        return None


def redistribute(tree, src, dst, telemetry=None, what="params"):
    """Map a host tree saved under layout ``src`` onto layout ``dst``.

    The tree must be fully addressable on this process (host numpy
    arrays, or replicated/single-device jax arrays) -- the
    restore-under-own-layout contract: callers first restore the
    snapshot with its OWN logical shapes replicated, then redistribute,
    then ``device_put`` onto the live shardings.  Covered conversions:

    - dp -> dp: N->M chunk-layout resize (trailing-pad/truncate flat
      planes; offset-preserving EF-residual re-partition);
    - pp -> pp: stage re-cutting (4-stage stacked -> 2-stage stacked);
    - pp <-> tp/ep/sp/replicated: stage-stacked <-> per-block trees;
    - ep -> ep expert-count re-cut (``num_experts`` in both planes):
      expert-stacked leading dims split k-for-1 / merge k-to-1 with
      the router's gate logits plane re-sized to match
      (``_reexpert`` -- grow->shrink is bit-identical);
    - scan <-> unrolled transformer block keying (``block_layout``);
    - tp/ep/sp <-> replicated: the logical tree is identical -- the
      call is then an audited identity (device placement is the
      caller's ``device_put``).

    Identical layouts return the tree untouched with no event; any
    actual redistribution emits a durable ``kind: "reshard"`` telemetry
    event (src/dst, planes moved, host bytes, wall seconds).
    """
    src = LayoutSpec.coerce(src)
    dst = LayoutSpec.coerce(dst)
    if src == dst:
        return tree
    t0 = time.perf_counter()
    out = _convert(tree, src, dst)
    wall = time.perf_counter() - t0
    planes, host_bytes = _tree_stats(out)
    log.info("resharded %s: %s -> %s (%d planes, %d host bytes, %.3fs)",
             what, src.describe(), dst.describe(), planes, host_bytes,
             wall)
    record_reshard_event(telemetry, src, dst, what, planes, host_bytes,
                         wall)
    return out


def to_model_layout(params, src_layout, model, telemetry=None,
                    what="params"):
    """Any snapshot params -> the built ``model``'s own (replicated)
    tree layout: the serving-refresh path.  ``src_layout`` may be a
    LayoutSpec or a manifest dict; dp flat planes unravel through the
    model's tree template, strategy/pp/scan trees restructure via
    ``redistribute``."""
    src = LayoutSpec.coerce(src_layout)
    dst = LayoutSpec.for_model(model)
    if src.kind == "dp":
        t0 = time.perf_counter()
        out = flat_to_tree(params, src, model.parameters()[0])
        planes, host_bytes = _tree_stats(out)
        record_reshard_event(telemetry, src, dst, what, planes,
                             host_bytes, time.perf_counter() - t0)
        return out
    return redistribute(params, src, dst, telemetry=telemetry, what=what)
