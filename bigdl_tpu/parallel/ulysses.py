"""Ulysses-style all-to-all sequence parallelism.

The second long-context strategy next to the ppermute ring
(parallel/ring_attention.py): instead of rotating K/V blocks around the
ring, one ``lax.all_to_all`` re-shards the activations from
sequence-sharded to HEAD-sharded, full attention runs locally on each
device's head slice, and a second all_to_all restores sequence sharding
(the DeepSpeed-Ulysses communication pattern -- PAPERS.md; public pattern,
re-implemented here on XLA collectives).

Trade-off vs ring: 2 all_to_alls of the activations per attention (cheap
on ICI, O(T*D/P) per device) and exact full-sequence attention with no
per-block online softmax; requires num_heads % P == 0.
"""

import jax
from jax import lax

from bigdl_tpu.nn.attention import dot_product_attention
from bigdl_tpu.utils.compat import axis_size


def ulysses_self_attention(q, k, v, axis_name, causal=False):
    """q, k, v: (N, T_local, H, Dh), sequence sharded over ``axis_name``
    (shard_map context).  -> (N, T_local, H, Dh).
    """
    p = axis_size(axis_name)
    h = q.shape[2]
    if h % p:
        raise ValueError(
            f"ulysses needs num_heads ({h}) divisible by the sequence "
            f"axis size ({p})")

    def seq_to_heads(x):
        # (N, T/P, H, Dh) -> (N, T, H/P, Dh)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    y = dot_product_attention(qg, kg, vg, causal=causal)
    return heads_to_seq(y)
