"""Expert parallelism: MoE expert-stacked params sharded over an ``expert``
mesh axis via GSPMD annotations.

No reference analogue (SURVEY.md section 2.4: expert parallelism absent).
The MoE layer (nn/moe.py) keeps experts stacked on a leading dimension; here
that dimension is annotated with ``NamedSharding(P("expert", ...))`` and the
batch with ``P("data")``.  XLA's SPMD partitioner then turns the
dispatch/combine einsums (``tec,td->ecd`` / ``tec,ecd->td``) into
all-to-all + local expert matmuls -- the same comm pattern hand-written EP
implementations build with ``lax.all_to_all``, derived automatically.
"""

import re
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.tree_util import keystr, tree_flatten_with_path, tree_unflatten

#: expert-stacked leaves: leading dim sharded over the expert axis.
MOE_EP_RULES = [
    (r"moe'\]\['w1", ("expert", None, None)),
    (r"moe'\]\['w2", ("expert", None, None)),
    (r"moe'\]\['b1", ("expert", None)),
    (r"moe'\]\['b2", ("expert", None)),
]


def ep_sharding_for_params(params, mesh, rules=MOE_EP_RULES):
    leaves, treedef = tree_flatten_with_path(params)
    out = []
    for path, leaf in leaves:
        name = keystr(path)
        spec = P()
        for pattern, dims in rules:
            if re.search(pattern, name):
                if len(dims) == getattr(leaf, "ndim", 0):
                    spec = P(*dims)
                break
        out.append(NamedSharding(mesh, spec))
    return tree_unflatten(treedef, out)


def ep_shard_params(params, mesh, rules=MOE_EP_RULES):
    return jax.tree.map(jax.device_put, params,
                        ep_sharding_for_params(params, mesh, rules))


def make_ep_train_step(model, criterion, optim_method, mesh,
                       data_axis: Optional[str] = "data",
                       aux_weight: float = 0.01, rules=MOE_EP_RULES,
                       compute_dtype=None):
    """-> compile_for(params) -> jitted step with expert-parallel params.

    Task loss + ``aux_weight``  x  router load-balance loss; expert params
    (and their optimizer moments) updated where their shard lives.
    """
    from bigdl_tpu.nn.module import has_frozen
    from bigdl_tpu.optim.train_step import _cast_tree
    if has_frozen(model):
        raise NotImplementedError(
            "freeze() is honored by make_train_step and the "
            "DistriOptimizer flat-chunk step; this model-parallel engine "
            "does not mask frozen parameters yet -- unfreeze() before "
            "building, or train with LocalOptimizer/DistriOptimizer")

    def _cast_ep_params(p):
        """Compute-dtype cast with the stacked-layout correction: expert
        biases are stored stacked as (E, features) -- rank 2 -- but are
        still VPU vector operands per expert, so they keep the fp32
        master treatment the rank rule gives unstacked biases (the MoE
        layer casts them at its use site, nn/moe.py:102-105)."""
        if compute_dtype is None:
            return p
        from jax.tree_util import keystr, tree_flatten_with_path, \
            tree_unflatten
        leaves, treedef = tree_flatten_with_path(p)
        out = []
        for path, leaf in leaves:
            bias_like = re.search(r"\['b[12]'\]$", keystr(path))
            if (jnp.issubdtype(leaf.dtype, jnp.floating)
                    and leaf.ndim >= 2 and not bias_like):
                leaf = leaf.astype(compute_dtype)
            out.append(leaf)
        return tree_unflatten(treedef, out)

    def step(params, opt_state, x, y, rng):
        def loss_fn(p):
            cp = _cast_ep_params(p)
            logits, st = model.apply(cp, (), x, training=True, rng=rng)
            task = criterion.apply(logits.astype(jnp.float32), y)
            return task + aux_weight * st["aux_loss"].astype(jnp.float32), \
                task

        (loss, task), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params)
        grads = _cast_tree(grads, jnp.float32)
        new_params, new_opt = optim_method.update(grads, opt_state, params)
        return new_params, new_opt, task

    def compile_for(params):
        from bigdl_tpu.parallel.zero import opt_state_shardings

        ps = ep_sharding_for_params(params, mesh, rules)
        batch_sh = NamedSharding(mesh, P(data_axis))
        rep = NamedSharding(mesh, P())
        # optimizer-state shardings pinned on BOTH sides (same fix as
        # parallel/tp.py): with the opt output left to propagation,
        # GSPMD picks an expert-sharded layout for the ROUTER's Adam
        # moments while the donated input plane is replicated, and XLA
        # refuses the alias at dispatch ("Expected aliased input ... to
        # have the same size") -- the 8-device ep dryrun failure
        opt_sh = opt_state_shardings(optim_method, params, ps, mesh)
        return jax.jit(
            step,
            in_shardings=(ps, opt_sh, batch_sh, batch_sh, rep),
            out_shardings=(ps, opt_sh, rep),
            donate_argnums=(0, 1),
        )

    return compile_for


def init_ep_opt_state(optim_method, params, mesh, rules=MOE_EP_RULES):
    """Optimizer moments sharded like their params; scalars replicated."""
    from bigdl_tpu.parallel.zero import shard_opt_state

    ps = ep_sharding_for_params(params, mesh, rules)
    return shard_opt_state(optim_method, params, ps, mesh)
