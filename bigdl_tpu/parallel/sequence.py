"""Sequence-parallel (and data x sequence) training steps.

The 'scale the sequence' capability (SURVEY.md section 5: greenfield).
Activations are sharded over the ``seq`` mesh axis; attention runs as a
ppermute ring (parallel/ring_attention.py); everything else in the
transformer is position-local, so the only other collectives are the
gradient pmean over the mesh.  Optimizer state is replicated here (the
ZeRO-1 path lives in optim/distri_optimizer.py; they compose in later
rounds via chunking over the data axis).
"""

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from bigdl_tpu.optim.train_step import _cast_params, _cast_tree
from bigdl_tpu.utils.compat import shard_map


def make_sp_train_step(model, criterion, optim_method, mesh,
                       seq_axis: str = "seq",
                       data_axis: Optional[str] = None,
                       compute_dtype=None):
    """-> jitted (params, opt_state, x, y, rng) -> (params, opt_state, loss).

    ``model`` must be built with ``seq_axis_name=seq_axis`` (e.g.
    TransformerLM) so its attention expects per-device sequence blocks.
    ``x``/``y``: (B, T) int token arrays, globally shaped; sharded
    (data_axis, seq_axis).
    """
    from bigdl_tpu.nn.module import has_frozen
    if has_frozen(model):
        raise NotImplementedError(
            "freeze() is honored by make_train_step and the "
            "DistriOptimizer flat-chunk step; this model-parallel engine "
            "does not mask frozen parameters yet -- unfreeze() before "
            "building, or train with LocalOptimizer/DistriOptimizer")
    axes = tuple(a for a in (data_axis, seq_axis) if a is not None)

    def step_body(params, opt_state, x, y, rng):
        for i, a in enumerate(axes):
            rng = jax.random.fold_in(rng, lax.axis_index(a) + i * 131)

        def loss_fn(p):
            cp = _cast_params(p, compute_dtype)
            out, _ = model.apply(cp, (), x, training=True, rng=rng)
            return criterion.apply(out.astype(jnp.float32), y)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = _cast_tree(grads, jnp.float32)
        # equal token counts per shard -> grad of the global mean loss is the
        # mean of shard grads
        grads = jax.tree.map(lambda g: lax.pmean(g, axes), grads)
        new_params, new_opt = optim_method.update(grads, opt_state, params)
        return new_params, new_opt, lax.pmean(loss, axes)

    batch_spec = P(data_axis, seq_axis)
    return jax.jit(shard_map(
        step_body,
        mesh=mesh,
        in_specs=(P(), P(), batch_spec, batch_spec, P()),
        out_specs=(P(), P(), P()),
        check_vma=False,
    ), donate_argnums=(0, 1))


def make_sp_eval_step(model, mesh, seq_axis: str = "seq",
                      data_axis: Optional[str] = None, compute_dtype=None):
    """-> jitted forward (params, x) -> fp32 logits for validation.

    The model's attention binds ``seq_axis`` via lax.axis_index, so plain
    ``jit`` cannot evaluate it -- the eval forward must run under the same
    shard_map topology as the train step."""

    def fwd(params, x):
        cp = _cast_params(params, compute_dtype)
        out, _ = model.apply(cp, (), x, training=False, rng=None)
        return out.astype(jnp.float32)

    batch_spec = P(data_axis, seq_axis)
    return jax.jit(shard_map(
        fwd, mesh=mesh,
        in_specs=(P(), batch_spec),
        out_specs=batch_spec,
        check_vma=False,
    ))


def shard_tokens(x, mesh, seq_axis="seq", data_axis=None):
    """Place a host token array with (data, seq) sharding."""
    import numpy as np

    sharding = NamedSharding(mesh, P(data_axis, seq_axis))
    return jax.make_array_from_process_local_data(sharding, np.asarray(x))
