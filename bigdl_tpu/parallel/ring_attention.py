"""Ring attention: exact attention over a sequence-sharded mesh axis.

No reference analogue (the reference predates transformers; SURVEY.md
section 5 lists long-context as greenfield) -- this is the north-star
'scale the sequence' capability, built the TPU way:

- the sequence axis is sharded over mesh axis ``axis_name``;
- K/V blocks rotate around the ring with ``lax.ppermute`` (neighbour ICI
  hops, no all-gather, so per-chip memory stays O(T_local));
- each hop updates a numerically-stable online softmax (flash-attention
  style: running max ``m``, normaliser ``l``, weighted accumulator ``o``),
  in fp32 regardless of input dtype;
- causal masking uses *global* positions derived from the block's origin
  device, so a fully-masked remote block contributes exactly zero.

Designed to run inside ``shard_map`` (per-device view).  Compute/communicate
overlap is left to XLA's latency-hiding scheduler (the ppermute for hop i+1
is independent of hop i's einsum).
"""

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from bigdl_tpu.utils.compat import shard_map


def ring_self_attention(q, k, v, axis_name: str, causal: bool = False):
    """Per-device blocks q,k,v: (B, T_local, H, Dh) -> (B, T_local, H, Dh).

    Exact (not approximate): equals single-device softmax attention on the
    gathered sequence, up to fp32 accumulation order.
    """
    n_dev = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    b, t, h, d = q.shape
    scale = 1.0 / math.sqrt(d)

    q32 = q.astype(jnp.float32)
    o0 = jnp.zeros((b, h, t, d), jnp.float32)
    l0 = jnp.zeros((b, h, t), jnp.float32)
    m0 = jnp.full((b, h, t), -jnp.inf, jnp.float32)

    qpos = my * t + jnp.arange(t)  # global positions of local queries

    def hop(carry, i):
        o, l, m, kb, vb = carry
        src = (my + i) % n_dev  # origin device of the current k/v block
        scores = jnp.einsum("bqhd,bkhd->bhqk", q32,
                            kb.astype(jnp.float32)) * scale
        if causal:
            kpos = src * t + jnp.arange(t)
            mask = (kpos[None, :] <= qpos[:, None]).astype(jnp.float32)
        else:
            mask = jnp.ones((t, t), jnp.float32)
        scores = jnp.where(mask > 0, scores, -jnp.inf)

        bm = jnp.max(scores, axis=-1)                      # (b,h,q)
        new_m = jnp.maximum(m, bm)
        safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
        p = jnp.exp(scores - safe_m[..., None]) * mask     # masked -> 0
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
        l = l * corr + jnp.sum(p, axis=-1)
        o = o * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, vb.astype(jnp.float32))

        # rotate k/v to the next device (receive the block of my+i+1)
        perm = [(j, (j - 1) % n_dev) for j in range(n_dev)]
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return (o, l, new_m, kb, vb), None

    (o, l, m, _, _), _ = lax.scan(hop, (o0, l0, m0, k, v),
                                  jnp.arange(n_dev))
    out = o / jnp.maximum(l, 1e-30)[..., None]             # (b,h,q,d)
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def sequence_shard_attention(q, k, v, mesh, axis_name="seq", causal=False):
    """Convenience wrapper: global (B, T, H, D) arrays -> shard_map'd ring."""
    from jax.sharding import PartitionSpec as P

    fn = shard_map(
        partial(ring_self_attention, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(P(None, axis_name), P(None, axis_name), P(None, axis_name)),
        out_specs=P(None, axis_name),
        check_vma=False,
    )
    return fn(q, k, v)
