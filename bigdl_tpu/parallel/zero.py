"""ZeRO-1 flat-parameter chunking.

Reference: parameters/AllReduceParameter.scala:84 -- BigDL flattens all
weights into one 1-D tensor, splits it into ``partitionNum`` chunks, and
each node owns the optimizer update for exactly one chunk
(optim/DistriOptimizer.scala:361-387).  That *is* ZeRO-1 (SURVEY.md
section 2.4), and we keep the same ownership layout on the TPU mesh:

- gradients:  ``reduce_scatter`` over the data axis -> each device holds the
  mean gradient for its chunk (the analogue of aggregateGradientPartition's
  fetch + fp16 tree-sum, AllReduceParameter.scala:228-270);
- update:     OptimMethod runs on the chunk only, so optimizer state
  (momentum/Adam moments) is sharded 1/N per device;
- weights:    ``all_gather`` rebuilds the replicated flat vector (the
  analogue of sendWeightPartition + getWeights, :193-220, :307-320).

Wire compression (the analogue of the reference's fp16-on-the-wire) is
a property of the COLLECTIVE's format, not of this layout: see
``ops/quantization.py`` (``CompressionSpec``) and the dp step's
quantized ``all_to_all`` path -- this module only guarantees the chunk
layout rounds to the quantization block (``block_size=``).  On-chip ICI
rarely needs it (bf16 compute is native); XLA picks the collective
algorithm.
"""

from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree


class FlatParamSpace:
    """Bijection between a params pytree and a padded flat fp32 vector.

    ``num_chunks`` devices each own ``chunk_size`` contiguous elements,
    mirroring the reference's chunk ownership
    (AllReduceParameter.scala:147-167).

    ``block_size > 1`` additionally rounds each chunk up to a whole
    number of quantization blocks, so the blockwise int8 wire format
    (``ops/quantization.py``) never straddles a chunk boundary: padding
    is chosen as the least multiple of ``num_chunks * block_size`` that
    holds every parameter.  The default (1) keeps the historical layout
    bit-for-bit.
    """

    def __init__(self, params_tree: Any, num_chunks: int,
                 block_size: int = 1):
        flat, self._unravel = ravel_pytree(params_tree)
        self.true_size = int(flat.size)
        self.num_chunks = int(num_chunks)
        self.block_size = max(1, int(block_size))
        unit = self.num_chunks * self.block_size
        self.padded_size = (self.true_size + unit - 1) // unit * unit
        self.chunk_size = self.padded_size // num_chunks
        self.dtype = flat.dtype

    def flatten(self, params_tree) -> jnp.ndarray:
        """Pytree -> padded flat vector.  Traceable."""
        flat, _ = ravel_pytree(params_tree)
        return jnp.pad(flat, (0, self.padded_size - self.true_size))

    def unflatten(self, flat: jnp.ndarray):
        """Padded flat vector -> pytree.  Traceable."""
        return self._unravel(flat[: self.true_size])

    def chunk(self, flat: jnp.ndarray, index) -> jnp.ndarray:
        return jax.lax.dynamic_slice(
            flat, (index * self.chunk_size,), (self.chunk_size,))


def refit_flat_plane(a, padded_size, true_size=None):
    """Re-fit a flat-plane leaf saved under one chunk layout onto
    another (N->M data-parallel resume, or an int8 block-rounding
    change): the layouts store the SAME ``true_size`` logical elements
    and differ only in trailing padding -- never read by the model math
    -- so leaves resize by zero-pad / tail-truncate on the last axis.
    Non-flat leaves (scalars, already-fitting vectors) pass through.
    ``true_size`` guards the truncation: shrinking below it would drop
    real parameters, which is a layout mismatch, not a padding change.

    Resume paths reach this through ``parallel/reshard.redistribute``
    (the general layout engine, which also emits the ``reshard`` audit
    event); this stays the one definition of the tail-refit math.
    """
    a = jnp.asarray(a)
    if a.ndim < 1 or a.shape[-1] == padded_size:
        return a
    if a.shape[-1] > padded_size:
        if true_size is not None and padded_size < true_size:
            raise ValueError(
                f"cannot refit a {a.shape[-1]}-element flat plane onto "
                f"padded size {padded_size} < true size {true_size}: "
                "the target layout holds fewer parameters than the "
                "snapshot")
        return a[..., :padded_size]
    pad = [(0, 0)] * (a.ndim - 1) + [(0, padded_size - a.shape[-1])]
    return jnp.pad(a, pad)


def repartition_ef_residual(ef, true_size, num_chunks, padded_size):
    """Re-partition the EF-SGD error-feedback residual plane
    (``ops/quantization.py``; one fp32 accumulated-quantization-error
    row per device) onto a DIFFERENT device count.

    Each device folds ITS row into its local gradient before
    quantizing, so the quantity the training trajectory depends on is
    the SUM over rows -- any row assignment preserving that sum applies
    the same total correction.  N->M therefore: sum the old rows into
    one global residual, drop the old layout's trailing padding
    (gradient there is identically 0, so its residual is too), re-pad
    to the new layout, and hand row j the slice in ITS chunk's global
    flat offsets (zeros elsewhere) -- no accumulated correction is
    dropped, and magnitude spreads evenly instead of piling onto one
    device."""
    ef = np.asarray(ef, np.float32)
    if ef.ndim != 2:
        raise ValueError(f"EF residual plane must be 2-D, got {ef.shape}")
    total = ef.sum(axis=0)[:min(int(true_size), ef.shape[1])]
    total = np.pad(total, (0, int(padded_size) - total.size))
    out = np.zeros((int(num_chunks), int(padded_size)), np.float32)
    chunk = int(padded_size) // int(num_chunks)
    for j in range(int(num_chunks)):
        out[j, j * chunk:(j + 1) * chunk] = total[j * chunk:(j + 1) * chunk]
    return out


def stage_batch_global(tree, sharding):
    """Host batch pytree -> global device arrays under ``sharding``.

    The per-step staging path of the dp driver
    (``DistriOptimizer._shard_batch``) and of the sharded serving
    engine (``bigdl_tpu/serving``): each host contributes its
    process-local rows and jax assembles the global array, so the same
    call works single-host (a plain sharded transfer) and multi-host
    (each process places its shard, no gather).  ``None`` subtrees
    (absent targets) pass through untouched.
    """
    if tree is None:
        return None
    to_global = lambda a: jax.make_array_from_process_local_data(
        sharding, np.asarray(a))
    return jax.tree.map(to_global, tree)


def opt_state_shardings(optim_method, params, param_shardings, mesh):
    """The sharding TREE ``shard_opt_state`` places with: moment
    subtrees (momentum/velocity/...) mirror the params tree and take
    the param shardings; anything else (step counters, scalars) is
    replicated.  Exposed separately so step builders can pin the SAME
    tree as ``out_shardings`` -- an output whose propagated sharding
    drifts from its donated input's loses the buffer alias (the exact
    leak ``tools/hlo_audit.py`` gates on)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    state_eval = jax.eval_shape(optim_method.init_state, params)
    rep = NamedSharding(mesh, P())
    param_struct = jax.tree.structure(param_shardings)
    out = {}
    for key, val in state_eval.items():
        # a moment subtree mirrors the params tree EXACTLY; anything
        # else (scalar counters, stats vectors) replicates.  The
        # structure check must be explicit: a scalar leaf is a valid
        # tree PREFIX of the shardings dict, so a prefix-tolerant map
        # would silently hand it the whole dict as its "sharding"
        if jax.tree.structure(val) == param_struct:
            out[key] = jax.tree.map(lambda _, s: s, val, param_shardings)
        else:
            out[key] = jax.tree.map(lambda a: rep, val)
    return out


def shard_opt_state(optim_method, params, param_shardings, mesh):
    """Optimizer state placed with the same shardings as its params
    (``opt_state_shardings``).  Shared by the tp/pp/ep engines -- the
    analogue of the reference owning OptimMethod state per weight chunk
    (optim/DistriOptimizer.scala:383).
    """
    state = optim_method.init_state(params)
    shardings = opt_state_shardings(optim_method, params,
                                    param_shardings, mesh)
    return jax.tree.map(jax.device_put, state, shardings)
