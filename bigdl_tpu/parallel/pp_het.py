"""Pipeline parallelism for arbitrary (uneven, heterogeneous) Sequential
models -- CNNs included.

Round-5 generalization of parallel/pp.py (VERDICT r4 ask #4): the stacked
GPipe path requires identical per-stage pytrees (homogeneous transformer
blocks).  Real models -- a ResNet-style CNN, a Sequential with mixed layer
types, uneven splits -- have per-stage parameter trees of DIFFERENT
structure and activation shapes that change across stage boundaries, so
neither the stage-stacked parameter layout nor the fixed-shape ppermute
ring applies.

TPU-native design:

- **Stage selection by ``lax.switch``**: every device runs the same SPMD
  program; ``lax.switch(axis_index(pipe), branches, buffer)`` picks the
  device's stage body.  All stage parameters ride in replicated (their
  bytes are small next to CNN activations); activations -- the dominant
  memory term -- are pipelined.
- **Padded flat ring buffer**: ``ppermute`` needs one static shape on
  every hop, so boundary activations are flattened to ``(mb, width)``
  and zero-padded to the widest boundary; each stage body unflattens its
  statically-known input shape, computes, and re-pads.  The pad bytes are
  dead stores XLA sinks into the same fusion as the stage compute.
- **GPipe schedule in one ``lax.scan``** (``n_micro + n_stages - 1``
  ticks), autodiff straight through -- the transpose of ``ppermute`` is
  the reverse-ring ``ppermute``, exactly as in parallel/pp.py.

Composes with data parallelism over a 2-D ``(data, pipe)`` mesh: batch
sharded over ``data``, shard_map's transpose inserts the gradient psums.
"""

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from bigdl_tpu.nn.module import child_rng
from bigdl_tpu.optim.train_step import _cast_params, _cast_tree
from bigdl_tpu.utils.compat import shard_map


def partition_sequential(model, n_stages: int,
                         boundaries: Optional[Sequence[int]] = None):
    """Split a built ``nn.Sequential`` into pipeline stages.

    ``boundaries``: child indices that START stages 1..n-1 (stage 0 starts
    at child 0); len == n_stages - 1.  Omitted -> auto-balance by
    parameter count (greedy prefix split).  Uneven and heterogeneous
    splits are the point: ``[2, 7, 9]`` gives stages of 2/5/2/rest
    children.

    -> (stage_slices, stage_params): per-stage (start, stop) child ranges
    and the per-stage parameter subtrees (different structures allowed).
    """
    n_children = len(model.modules)
    if boundaries is None:
        sizes = [sum(int(np.prod(l.shape))
                     for l in jax.tree.leaves(model._params[str(i)]))
                 for i in range(n_children)]
        total = sum(sizes)
        # greedy: cut whenever the running stage reaches its fair share,
        # leaving enough children for the remaining stages
        boundaries = []
        acc = 0
        for i, s in enumerate(sizes):
            acc += s
            if (len(boundaries) < n_stages - 1
                    and acc >= total / n_stages
                    and n_children - (i + 1) >= n_stages - 1 - len(boundaries)):
                boundaries.append(i + 1)
                acc = 0
        while len(boundaries) < n_stages - 1:   # param-less tails
            boundaries.append(n_children - (n_stages - 1 - len(boundaries)))
    boundaries = list(boundaries)
    if len(boundaries) != n_stages - 1:
        raise ValueError(
            f"need {n_stages - 1} boundaries for {n_stages} stages, got "
            f"{len(boundaries)}")
    cuts = [0] + boundaries + [n_children]
    if any(cuts[i] >= cuts[i + 1] for i in range(n_stages)):
        raise ValueError(f"empty stage in boundaries {boundaries} "
                         f"({n_children} children)")
    slices = [(cuts[i], cuts[i + 1]) for i in range(n_stages)]
    stage_params = [
        {str(j): model._params[str(j)] for j in range(a, b)}
        for a, b in slices
    ]
    return slices, stage_params


def _boundary_specs(model, slices, input_spec):
    """Activation spec entering each stage (index 0 = model input) plus
    the final output spec."""
    specs = [input_spec]
    spec = input_spec
    for i, layer in enumerate(model.modules):
        p, s = model._params[str(i)], model._state[str(i)]
        spec = layer.output_spec(p, s, spec)
        for a, b in slices[1:]:
            if i + 1 == a:
                specs.append(spec)
    return specs, spec


def make_het_pp_train_step(model, criterion, optim_method, mesh,
                           n_microbatches: int, input_spec,
                           boundaries: Optional[Sequence[int]] = None,
                           pipe_axis: str = "pipe",
                           data_axis: Optional[str] = None,
                           compute_dtype=None):
    """-> (step, stage_params) for an arbitrary Sequential.

    ``step(stage_params, opt_state, x, y, rng) -> (params, opt, loss)``
    (the shared strategy-step convention).  ``stage_params`` is the
    list-of-subtrees pytree from partition_sequential -- replicated on
    every device; optimizer state mirrors it.

    ``input_spec``: ShapeDtypeStruct of one MICROBATCH (local to the data
    shard), e.g. ``(mb, H, W, C)`` -- boundary shapes are inferred from
    it, so it must be the true per-device microbatch shape.
    """
    from bigdl_tpu.nn.module import has_frozen
    if has_frozen(model):
        raise NotImplementedError(
            "freeze() is not honored by the pipeline engines; unfreeze() "
            "or train with LocalOptimizer/DistriOptimizer")
    if any(jnp.issubdtype(getattr(l, "dtype", jnp.int32), jnp.floating)
           for l in jax.tree.leaves(model._state)):
        raise NotImplementedError(
            "pipelined Sequential with floating module state (BatchNorm "
            "running stats) is not supported; swap BN for a stateless "
            "normalization or train data-parallel")

    n_stages = mesh.shape[pipe_axis]
    slices, init_stage_params = partition_sequential(
        model, n_stages, boundaries)
    # fresh buffers: the returned step donates its params argument, and the
    # partition subtrees alias model._params -- donating those would leave
    # the model holding deleted arrays
    init_stage_params = jax.tree.map(jnp.array, init_stage_params)
    bspecs, out_spec = _boundary_specs(model, slices, input_spec)
    cdt = compute_dtype or jnp.float32

    mb = input_spec.shape[0]
    widths = [int(np.prod(s.shape[1:])) for s in bspecs]
    out_width = int(np.prod(out_spec.shape[1:]))
    width = max(widths + [out_width])

    def stage_body(s, stage_params, flat_in, rng):
        a, b = slices[s]
        x = flat_in[:, :widths[s]].reshape(
            (mb,) + bspecs[s].shape[1:]).astype(
                bspecs[s].dtype if not jnp.issubdtype(
                    bspecs[s].dtype, jnp.floating) else cdt)
        for j in range(a, b):
            x, _ = model.modules[j].apply(
                stage_params[str(j)], model._state[str(j)], x,
                training=True, rng=child_rng(rng, j))
        flat = x.reshape(mb, -1).astype(cdt)
        pad = width - flat.shape[1]
        return jnp.pad(flat, ((0, 0), (0, pad))) if pad else flat

    def per_device(stage_params_list, x, y, rng):
        # x: (n_micro, mb, ...) local shard; y: (n_micro, mb, ...)
        stage = lax.axis_index(pipe_axis)
        n_micro = x.shape[0]
        fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        branches = [
            lambda flat, rng, s=s: stage_body(
                s, _cast_params(stage_params_list[s], compute_dtype),
                flat, rng)
            for s in range(n_stages)
        ]

        def embed_input(m_idx):
            flat = x[m_idx].reshape(mb, -1).astype(cdt)
            pad = width - flat.shape[1]
            return jnp.pad(flat, ((0, 0), (0, pad))) if pad else flat

        def tick(carry, tk):
            recv, outs = carry
            m_idx = jnp.clip(tk, 0, n_micro - 1)
            inp = jnp.where(stage == 0, embed_input(m_idx), recv)
            out = lax.switch(stage, branches, inp, child_rng(rng, tk))
            out_idx = tk - (n_stages - 1)
            valid = (stage == n_stages - 1) & (out_idx >= 0)
            widx = jnp.clip(out_idx, 0, n_micro - 1)
            outs = outs.at[widx].set(jnp.where(valid, out, outs[widx]))
            send = lax.ppermute(out, pipe_axis, fwd_perm)
            return (send, outs), None

        init = (jnp.zeros((mb, width), cdt),
                jnp.zeros((n_micro, mb, width), cdt))
        (_, outs), _ = lax.scan(tick, init,
                                jnp.arange(n_micro + n_stages - 1))
        logits = outs[:, :, :out_width].reshape(
            (n_micro * mb,) + out_spec.shape[1:]).astype(jnp.float32)
        yf = y.reshape((n_micro * mb,) + y.shape[2:])
        loss_local = criterion.apply(logits, yf)
        loss = lax.psum(
            jnp.where(stage == n_stages - 1, loss_local, 0.0), pipe_axis)
        if data_axis is not None:
            loss = lax.pmean(loss, data_axis)
        return loss

    batch_spec = P(None, data_axis) if data_axis else P()
    smapped = shard_map(
        per_device, mesh=mesh,
        in_specs=(P(), batch_spec, batch_spec, P()),
        out_specs=P(),
        check_vma=False,
    )

    data_size = mesh.shape[data_axis] if data_axis else 1
    expected_n = n_microbatches * data_size * mb

    def loss_fn(stage_params_list, x, y, rng):
        n = x.shape[0]
        if n != expected_n:
            # the stage bodies bake the microbatch shape from input_spec;
            # a drifting batch (e.g. a short final minibatch) must fail
            # with the cause, not a reshape error inside the scan
            raise ValueError(
                f"batch {n} != the compiled pipeline batch {expected_n} "
                f"({n_microbatches} microbatches x {data_size} data "
                f"shards x microbatch {mb}); use SampleToMiniBatch"
                f"(..., drop_remainder=True) or a batch-preserving "
                f"dataset")
        xm = x.reshape((n_microbatches, n // n_microbatches) + x.shape[1:])
        ym = y.reshape((n_microbatches, n // n_microbatches) + y.shape[1:])
        return smapped(stage_params_list, xm, ym, rng)

    def step(stage_params_list, opt_state, x, y, rng):
        loss, grads = jax.value_and_grad(loss_fn)(stage_params_list, x, y,
                                                  rng)
        grads = _cast_tree(grads, jnp.float32)
        new_params, new_opt = optim_method.update(grads, opt_state,
                                                  stage_params_list)
        return new_params, new_opt, loss

    return jax.jit(step, donate_argnums=(0, 1)), init_stage_params


def merge_stage_params(model, stage_params_list):
    """Fold per-stage subtrees back into the Sequential's params dict."""
    out = {}
    for sub in stage_params_list:
        out.update(sub)
    return out
