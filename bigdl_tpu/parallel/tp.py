"""Tensor parallelism via GSPMD sharding rules.

No reference analogue (SURVEY.md section 2.4: tensor parallelism absent) --
built the canonical TPU way: annotate parameter shardings over a ``model``
mesh axis with ``NamedSharding`` and let XLA's SPMD partitioner insert the
collectives (all-gather/reduce-scatter on ICI).  Megatron-style layout for
the transformer: column-parallel qkv/fc1 (output dim sharded), row-parallel
out/fc2 (input dim sharded), so each block needs exactly one psum per
sub-layer, which GSPMD derives automatically from these annotations.
"""

import re
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.tree_util import keystr, tree_flatten_with_path, tree_unflatten

from bigdl_tpu.optim.train_step import _cast_params, _cast_tree

#: path-regex -> per-dim sharding over the model axis.  None entries mean
#: replicated.  Applied to TransformerLM parameter paths.
TRANSFORMER_TP_RULES = [
    (r"qkv_weight", ("model", None)),     # column parallel (heads sharded)
    (r"qkv_bias", ("model",)),
    (r"out_weight", (None, "model")),     # row parallel
    (r"fc1'\]\['weight", ("model", None)),
    (r"fc1'\]\['bias", ("model",)),
    (r"fc2'\]\['weight", (None, "model")),
    (r"\['head'\]$", ("model", None)),    # vocab-sharded lm head
]


def sharding_for_params(params, mesh, rules=TRANSFORMER_TP_RULES):
    """-> pytree of NamedSharding matching ``rules`` by parameter path."""
    leaves, treedef = tree_flatten_with_path(params)
    out = []
    for path, leaf in leaves:
        name = keystr(path)
        spec = P()
        for pattern, dims in rules:
            if re.search(pattern, name):
                if len(dims) == getattr(leaf, "ndim", 0):
                    spec = P(*dims)
                break
        out.append(NamedSharding(mesh, spec))
    return tree_unflatten(treedef, out)


def shard_params(params, mesh, rules=TRANSFORMER_TP_RULES):
    shardings = sharding_for_params(params, mesh, rules)
    return jax.tree.map(jax.device_put, params, shardings)


def make_tp_train_step(model, criterion, optim_method, mesh,
                       data_axis: Optional[str] = "data",
                       rules=TRANSFORMER_TP_RULES, compute_dtype=None):
    """-> jitted GSPMD train step with tensor-parallel params.

    ``x``/``y`` batch-sharded over ``data_axis``; params sharded per rules;
    optimizer state inherits the param shardings (each device updates only
    its param shard -- optimizer-state parallelism for free).
    """
    from bigdl_tpu.nn.module import has_frozen
    if has_frozen(model):
        raise NotImplementedError(
            "freeze() is honored by make_train_step and the "
            "DistriOptimizer flat-chunk step; this model-parallel engine "
            "does not mask frozen parameters yet -- unfreeze() before "
            "building, or train with LocalOptimizer/DistriOptimizer")

    def step(params, opt_state, x, y, rng):
        def loss_fn(p):
            cp = _cast_params(p, compute_dtype)
            out, _ = model.apply(cp, (), x, training=True, rng=rng)
            return criterion.apply(out.astype(jnp.float32), y)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = _cast_tree(grads, jnp.float32)
        new_params, new_opt = optim_method.update(grads, opt_state, params)
        return new_params, new_opt, loss

    def compile_for(params):
        from bigdl_tpu.parallel.zero import opt_state_shardings

        ps = sharding_for_params(params, mesh, rules)
        batch_sh = NamedSharding(mesh, P(data_axis))
        rep = NamedSharding(mesh, P())
        # optimizer-state shardings pinned on BOTH sides: with the
        # output sharding left to propagation, GSPMD occasionally picks
        # a different layout for a moment plane than its donated input
        # carries, and XLA silently drops that buffer's alias -- the
        # plane is then double-buffered (caught by tools/hlo_audit.py)
        opt_sh = opt_state_shardings(optim_method, params, ps, mesh)
        return jax.jit(
            step,
            in_shardings=(ps, opt_sh, batch_sh, batch_sh, rep),
            out_shardings=(ps, opt_sh, rep),
            donate_argnums=(0, 1),
        )

    return compile_for


def init_opt_state_sharded(optim_method, params, mesh,
                           rules=TRANSFORMER_TP_RULES):
    """Optimizer state placed with the same shardings as its params
    (moments shard like weights; scalars replicated)."""
    from bigdl_tpu.parallel.zero import shard_opt_state

    ps = sharding_for_params(params, mesh, rules)
    return shard_opt_state(optim_method, params, ps, mesh)
