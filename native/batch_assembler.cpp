// Native host-side batch assembler.
//
// Reference: the multi-threaded batch builders MTLabeledBGRImgToBatch
// (dataset/image/MTLabeledBGRImgToBatch.scala) and MTImageFeatureToBatch
// (transform/vision/image/MTImageFeatureToBatch.scala), which fan sample
// copy/normalize work across JVM threads before feeding the optimizer.
//
// TPU-native equivalent: the device never sees this path -- it is pure host
// work feeding the jit'd step, so it is written as a small C++ kernel
// (std::thread fan-out, no JVM, no OpenCV JNI).  Exposed to Python via
// ctypes (no pybind11 in the image).  The ctypes call releases the GIL, so
// Python-side prefetch threads get true parallelism.
//
// Build: g++ -O3 -march=native -shared -fPIC -o libbatch_assembler.so \
//            batch_assembler.cpp -lpthread

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// Copy + normalize one sample: out = (src - mean) / std, channel-wise.
void assemble_one(const float* src, float* out, int64_t sample_size,
                  const float* mean, const float* stdv, int channels) {
  if (channels <= 0) {
    std::memcpy(out, src, sample_size * sizeof(float));
    return;
  }
  const int64_t pixels = sample_size / channels;
  for (int64_t p = 0; p < pixels; ++p) {
    const float* s = src + p * channels;
    float* d = out + p * channels;
    for (int c = 0; c < channels; ++c) {
      d[c] = (s[c] - mean[c]) / stdv[c];
    }
  }
}

void run_range(const float* src, const int64_t* indices, int64_t begin,
               int64_t end, int64_t sample_size, const float* mean,
               const float* stdv, int channels, float* out) {
  for (int64_t i = begin; i < end; ++i) {
    assemble_one(src + indices[i] * sample_size, out + i * sample_size,
                 sample_size, mean, stdv, channels);
  }
}

}  // namespace

extern "C" {

// Gather samples by index from a contiguous pool and channel-normalize into
// a batch buffer, fanning the work over n_threads.
//   src:      (pool_size, sample_size) float32, C-contiguous
//   indices:  (batch,) int64 rows to gather
//   out:      (batch, sample_size) float32, preallocated
//   mean/stdv:(channels,) or channels==0 for plain copy
void bigdl_gather_normalize(const float* src, const int64_t* indices,
                            int64_t batch, int64_t sample_size,
                            const float* mean, const float* stdv,
                            int channels, float* out, int n_threads) {
  n_threads = std::max(1, std::min<int>(n_threads, (int)batch));
  if (n_threads == 1) {
    run_range(src, indices, 0, batch, sample_size, mean, stdv, channels, out);
    return;
  }
  std::vector<std::thread> workers;
  workers.reserve(n_threads);
  const int64_t chunk = (batch + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    const int64_t b = t * chunk;
    const int64_t e = std::min(batch, b + chunk);
    if (b >= e) break;
    workers.emplace_back(run_range, src, indices, b, e, sample_size, mean,
                         stdv, channels, out);
  }
  for (auto& w : workers) w.join();
}

// int labels gather (no normalize).
void bigdl_gather_labels(const int32_t* src, const int64_t* indices,
                         int64_t batch, int64_t label_size, int32_t* out) {
  for (int64_t i = 0; i < batch; ++i) {
    std::memcpy(out + i * label_size, src + indices[i] * label_size,
                label_size * sizeof(int32_t));
  }
}

}  // extern "C"
