// Native TFRecord reader: framing + masked crc32c validation in C++.
//
// The runtime analogue of the reference's native data-loader layer
// (SURVEY.md 2.8: IO/codec work stays off the accelerator; the reference
// does it in JNI/OpenCV land, here a small C++ reader feeds the host
// pipeline).  Wire format per record (see interop/tfrecord.py):
//
//   uint64 LE length | uint32 LE masked_crc(length) |
//   payload[length]  | uint32 LE masked_crc(payload)
//
// C API (ctypes, no pybind11):
//   void*       rr_open(const char* path);
//   long long   rr_next(void* h);   // >=0 payload len, -1 EOF, -2 corrupt
//   const unsigned char* rr_data(void* h);
//   void        rr_close(void* h);

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

uint32_t crc_table[256];
bool table_ready = false;

void init_table() {
  if (table_ready) return;
  const uint32_t poly = 0x82F63B78u;  // Castagnoli, reflected
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k) c = (c & 1) ? (poly ^ (c >> 1)) : (c >> 1);
    crc_table[i] = c;
  }
  table_ready = true;
}

uint32_t crc32c(const uint8_t* data, size_t n) {
  init_table();
  uint32_t crc = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; ++i)
    crc = crc_table[(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
}

uint32_t masked_crc(const uint8_t* data, size_t n) {
  uint32_t crc = crc32c(data, n);
  return (((crc >> 15) | (crc << 17)) + 0xA282EAD8u);
}

struct Reader {
  FILE* f;
  std::vector<uint8_t> buf;
};

}  // namespace

extern "C" {

void* rr_open(const char* path) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  Reader* r = new Reader();
  r->f = f;
  return r;
}

long long rr_next(void* handle) {
  Reader* r = static_cast<Reader*>(handle);
  uint8_t head[8];
  size_t got = std::fread(head, 1, 8, r->f);
  if (got == 0) return -1;  // clean EOF
  if (got < 8) return -2;
  uint64_t len = 0;
  std::memcpy(&len, head, 8);  // little-endian hosts only (x86/arm)
  uint32_t len_crc = 0;
  if (std::fread(&len_crc, 1, 4, r->f) != 4) return -2;
  if (masked_crc(head, 8) != len_crc) return -2;
  r->buf.resize(len);
  if (len && std::fread(r->buf.data(), 1, len, r->f) != len) return -2;
  uint32_t data_crc = 0;
  if (std::fread(&data_crc, 1, 4, r->f) != 4) return -2;
  if (masked_crc(r->buf.data(), len) != data_crc) return -2;
  return static_cast<long long>(len);
}

const unsigned char* rr_data(void* handle) {
  return static_cast<Reader*>(handle)->buf.data();
}

void rr_close(void* handle) {
  Reader* r = static_cast<Reader*>(handle);
  if (r->f) std::fclose(r->f);
  delete r;
}

}  // extern "C"
